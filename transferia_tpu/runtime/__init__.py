"""Execution runtimes (reference: pkg/runtime/local/)."""

from transferia_tpu.runtime.local import LocalWorker, run_replication

__all__ = ["LocalWorker", "run_replication"]
