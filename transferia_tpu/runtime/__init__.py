"""Execution runtimes (reference: pkg/runtime/local/).

`local` pulls the whole factory chain (sources, sinks, coordinator), so
it is imported lazily: leaf modules in this package (`knobs`,
`lockwatch`) must stay importable from anywhere in the tree without
dragging the heavy graph in — they are imported by the very modules
`local` depends on.
"""

__all__ = ["LocalWorker", "run_replication"]


def __getattr__(name):
    if name in __all__:
        from transferia_tpu.runtime import local

        return getattr(local, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
