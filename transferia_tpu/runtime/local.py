"""Local replication runtime.

Reference parity: pkg/runtime/local/replication_sync_runtime.go:21-155
(LocalWorker), replicationstrategy/basic_strategy.go:23-139 (source ->
async-sink pump), replication.go:91-191 (infinite retry loop, 10s backoff,
fatal-error classification, 1m heartbeats).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from transferia_tpu.abstract.errors import is_fatal
from transferia_tpu.coordinator.interface import Coordinator, TransferStatus
from transferia_tpu.factories import make_async_sink, new_source
from transferia_tpu.middlewares.asynchronizer import ErrorTracker
from transferia_tpu.stats import trace
from transferia_tpu.stats.ledger import LEDGER
from transferia_tpu.stats.registry import Metrics, ReplicationStats

logger = logging.getLogger(__name__)

RETRY_BACKOFF_SECONDS = 10.0   # replication.go sleep between attempts
HEARTBEAT_SECONDS = 60.0       # replication.go:72-74


class LocalWorker:
    """One replication attempt: build source + sink, pump until stop/error."""

    def __init__(self, transfer, coordinator: Coordinator,
                 metrics: Optional[Metrics] = None):
        self.transfer = transfer
        self.cp = coordinator
        self.metrics = metrics or Metrics()
        self.source = None
        self.sink: Optional[ErrorTracker] = None
        self._error: Optional[BaseException] = None

    def run(self) -> None:
        """Blocks until the source stops or fails (BasicStrategy.Run)."""
        self.sink = make_async_sink(self.transfer, self.metrics,
                                    snapshot_stage=False)
        self.source = new_source(self.transfer, self.metrics,
                                 coordinator=self.cp)
        # root span for the whole attempt: per-batch spans recorded by
        # parsequeue / middlewares on worker threads share its timeline
        sp = trace.span("replication_attempt")
        if sp:
            sp.add(transfer_id=self.transfer.id)
        try:
            with sp:
                self.source.run(self.sink)
            # surface sink-side failures latched by the error tracker
            if isinstance(self.sink, ErrorTracker) and self.sink.failure:
                raise self.sink.failure
        finally:
            self.sink.close()

    def stop(self) -> None:
        if self.source is not None:
            self.source.stop()


def is_partitioned_replication(transfer) -> bool:
    """queue -> object-storage replication runs one pipeline per
    partition (replicationstrategy/partitioned_strategy.go, chosen by
    IsQueueToS3Replication in replication_sync_runtime.go:134-136)."""
    src_p = getattr(transfer.src, "PROVIDER", "")
    dst_p = getattr(transfer.dst, "PROVIDER", "")
    return src_p in ("kafka", "eventhub") and dst_p in ("s3", "fs")


class PartitionedWorker:
    """One independent source+sink pipeline per topic partition: a slow
    object flush on one partition never backpressures the others, and
    per-partition file rotation gets clean offset ranges."""

    def __init__(self, transfer, coordinator: Coordinator,
                 metrics: Optional[Metrics] = None):
        self.transfer = transfer
        self.cp = coordinator
        self.metrics = metrics or Metrics()
        self._pipelines: list = []  # (source, sink)
        self._stopped = threading.Event()
        self._plock = threading.Lock()  # guards pipelines vs stop()
        # every pump-thread failure (run, latched sink, close) lands
        # here; run() re-raises the first one.  Readable after stop()
        # so callers never lose an error to a daemon thread's death.
        self.failures: list[BaseException] = []
        self._err_lock = threading.Lock()

    def _kafka_params(self):
        src = self.transfer.src
        if getattr(src, "PROVIDER", "") == "eventhub":
            return src.to_kafka_params()
        return src

    def run(self) -> None:
        from transferia_tpu.providers.kafka.provider import (
            _KafkaQueueClient,
            topic_partitions,
        )
        from transferia_tpu.providers.queue_common import QueueSource

        params = self._kafka_params()
        partitions = topic_partitions(params)
        if not partitions:
            raise RuntimeError(f"topic {params.topic!r} has no partitions")
        logger.info("partitioned replication: %d pipelines (%s)",
                    len(partitions), partitions)
        threads = []
        for p in partitions:
            if self._stopped.is_set():
                break  # stop() fired while pipelines were being built
            client = _KafkaQueueClient(params, self.transfer.id,
                                       self.cp, partitions=[p])
            source = QueueSource(
                client, self.transfer.src.parser_config(),
                parallelism=max(
                    1, self.transfer.src.parallelism // len(partitions)),
                metrics=self.metrics, transfer_id=self.transfer.id)
            sink = make_async_sink(self.transfer, self.metrics,
                                   snapshot_stage=False)
            with self._plock:
                self._pipelines.append((source, sink))
                if self._stopped.is_set():
                    # stop() already swept: this source would be missed
                    source.stop()

            def pump(src=source, snk=sink, part=p):
                try:
                    src.run(snk)
                    if isinstance(snk, ErrorTracker) and snk.failure:
                        raise snk.failure
                except BaseException as e:
                    self._record_failure(part, e)
                    self.stop()  # one failure restarts the attempt
                finally:
                    # a close() error (flush of buffered rows, broken
                    # connection teardown) must surface on run() like
                    # any pump error — not die with the daemon thread
                    try:
                        snk.close()
                    except BaseException as e:
                        self._record_failure(part, e)
                        self.stop()

            t = threading.Thread(target=pump, daemon=True,
                                 name=f"partition-{p}")
            threads.append(t)
            t.start()
        for t in threads:
            t.join()
        if self.failures:
            raise self.failures[0]

    def _record_failure(self, partition: int, err: BaseException) -> None:
        with self._err_lock:
            self.failures.append(err)
        logger.warning("partition %d pipeline failed: %s", partition, err)

    @property
    def failure(self) -> Optional[BaseException]:
        """First pump failure, if any (readable after stop())."""
        with self._err_lock:
            return self.failures[0] if self.failures else None

    def stop(self) -> None:
        self._stopped.set()
        with self._plock:
            for source, _sink in self._pipelines:
                source.stop()


def run_replication(transfer, coordinator: Coordinator,
                    metrics: Optional[Metrics] = None,
                    stop_event: Optional[threading.Event] = None,
                    max_attempts: int = 0,
                    backoff: float = RETRY_BACKOFF_SECONDS) -> None:
    """The infinite retry loop (replication.go:91-191).

    Restarts the worker on retriable errors with a fixed backoff; a fatal
    error fails the transfer and raises.  stop_event ends the loop cleanly.
    max_attempts=0 means retry forever.
    """
    metrics = metrics or Metrics()
    stats = ReplicationStats(metrics)
    stop_event = stop_event or threading.Event()
    attempt = 0
    while not stop_event.is_set():
        attempt += 1
        worker = (PartitionedWorker(transfer, coordinator, metrics)
                  if is_partitioned_replication(transfer)
                  else LocalWorker(transfer, coordinator, metrics))
        coordinator.set_status(transfer.id, TransferStatus.RUNNING)
        stats.running.set(1)

        stopper = threading.Thread(
            target=_stop_on_event, args=(stop_event, worker), daemon=True
        )
        stopper.start()
        heartbeat = threading.Thread(
            target=_heartbeat_loop,
            args=(stop_event, coordinator, transfer.id, metrics),
            daemon=True,
        )
        heartbeat.start()
        try:
            worker.run()
            if stop_event.is_set():
                logger.info("replication stopped by request")
                return
            # source returned without stop: treat as retriable interruption
            raise ConnectionError("source terminated unexpectedly")
        except BaseException as e:
            stats.running.set(0)
            if stop_event.is_set():
                logger.info("replication stopped during error: %s", e)
                return
            if is_fatal(e):
                stats.fatal_errors.inc()
                logger.error("fatal replication error: %s", e)
                coordinator.fail_replication(transfer.id, str(e))
                raise
            stats.restarts.inc()
            logger.warning("replication attempt %d failed, retrying in "
                           "%.0fs: %s", attempt, backoff, e)
            if max_attempts and attempt >= max_attempts:
                coordinator.fail_replication(transfer.id, str(e))
                raise
            stop_event.wait(backoff)


def run_regular_snapshot(transfer, coordinator: Coordinator,
                         metrics: Optional[Metrics] = None,
                         stop_event: Optional[threading.Event] = None,
                         max_runs: int = 0) -> None:
    """Cron-driven re-snapshot loop (pkg/abstract/regular_snapshot.go +
    helm CronJob).  Each tick runs an incremental-aware upload of all
    tables; cursors persist through the coordinator."""
    from transferia_tpu.tasks.snapshot import SnapshotLoader
    from transferia_tpu.utils.cron import parse_cron

    rs = transfer.regular_snapshot
    if not rs.enabled or not rs.cron:
        raise ValueError("transfer has no regular_snapshot cron configured")
    spec = parse_cron(rs.cron)
    stop_event = stop_event or threading.Event()
    runs = 0
    while not stop_event.is_set():
        next_t = spec.next_after()
        wait = max(0.0, next_t - time.time())
        logger.info("regular snapshot: next run in %.0fs", wait)
        if stop_event.wait(wait):
            return
        loader = SnapshotLoader(
            transfer, coordinator,
            operation_id=f"op-{transfer.id}-{int(next_t)}",
            metrics=metrics,
        )
        loader.upload_tables()
        runs += 1
        if max_runs and runs >= max_runs:
            return


def _stop_on_event(stop_event: threading.Event, worker: LocalWorker) -> None:
    stop_event.wait()
    worker.stop()


def _heartbeat_loop(stop_event: threading.Event, cp: Coordinator,
                    transfer_id: str,
                    metrics: Optional[Metrics] = None) -> None:
    from transferia_tpu.stats import fleetobs

    exporter = None
    if getattr(cp, "supports_obs_segments", lambda: False)():
        exporter = fleetobs.exporter_for(cp, f"repl-{transfer_id}")
    while not stop_event.wait(HEARTBEAT_SECONDS):
        cp.transfer_health(transfer_id, healthy=True)
        if metrics is not None:
            # device counters ride the heartbeat onto this pipeline's
            # metrics so long replications expose them, not just the
            # one-shot trace/snapshot paths; the attribution ledger
            # folds on the same heartbeat
            trace.TELEMETRY.fold_into(metrics)
            LEDGER.fold_into(metrics)
            if exporter is not None:
                # obs segments (spans, hists, watermarks) ride the same
                # beat: a long replication's freshness is visible
                # fleet-wide, and SLO burn rates get their window edges
                exporter.export("periodic")
                from transferia_tpu.stats import slo

                slo.fold_verdicts(metrics, slo.debug_slo())
    if exporter is not None:
        exporter.export("final")
