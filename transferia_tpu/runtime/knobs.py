"""Central registry for environment knobs (`TRANSFERIA_TPU_*`, `BENCH_*`).

Every env-tunable in the tree reads through one of the helpers here so
that (a) the full knob surface is enumerable at runtime
(`registered_knobs()`), and (b) the KNB001 static rule can cross-check
code against the README knob table: a knob read anywhere else is
"undocumented plumbing", a README row naming a knob nobody reads is a
dead doc row.

Helpers read the environment at *call* time (not import time) so tests
can monkeypatch `os.environ`; each also takes an explicit ``environ``
mapping for call sites that already thread one through (coordinator
lease tunables, snapshot tuning).

This module is deliberately a leaf: stdlib imports only.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Mapping, Optional

__all__ = [
    "Knob",
    "env_bool",
    "env_float",
    "env_int",
    "env_raw",
    "env_str",
    "registered_knobs",
]


@dataclass(frozen=True)
class Knob:
    """One registered env knob: name, value kind, and its default."""

    name: str
    kind: str            # str | raw | int | float | bool
    default: object


_REGISTRY: dict[str, Knob] = {}
_REG_LOCK = threading.Lock()

# strings that read as False for env_bool; anything else non-empty is
# True (matches the tree's dominant `!= "0"` / kill-switch idiom)
_FALSY = frozenset({"0", "false", "no", "off"})


def _register(name: str, kind: str, default: object) -> None:
    with _REG_LOCK:
        if name not in _REGISTRY:
            _REGISTRY[name] = Knob(name, kind, default)


def registered_knobs() -> dict[str, Knob]:
    """Snapshot of every knob read so far in this process."""
    with _REG_LOCK:
        return dict(_REGISTRY)


def _lookup(name: str, environ: Optional[Mapping[str, str]]):
    env = os.environ if environ is None else environ
    return env.get(name)


def env_raw(name: str,
            environ: Optional[Mapping[str, str]] = None) -> Optional[str]:
    """The raw value, or None when unset — for knobs whose *presence*
    is the signal (auto-vs-pinned tri-states like CHUNK_ROWS/LINK)."""
    _register(name, "raw", None)
    return _lookup(name, environ)


def env_str(name: str, default: str = "",
            environ: Optional[Mapping[str, str]] = None) -> str:
    _register(name, "str", default)
    v = _lookup(name, environ)
    return default if v is None else v


def env_int(name: str, default: int,
            environ: Optional[Mapping[str, str]] = None) -> int:
    _register(name, "int", default)
    v = _lookup(name, environ)
    if v is None or not str(v).strip():
        return default
    try:
        return int(str(v).strip())
    except ValueError:
        return default


def env_float(name: str, default: float,
              environ: Optional[Mapping[str, str]] = None) -> float:
    _register(name, "float", default)
    v = _lookup(name, environ)
    if v is None or not str(v).strip():
        return default
    try:
        return float(str(v).strip())
    except ValueError:
        return default


def env_bool(name: str, default: bool,
             environ: Optional[Mapping[str, str]] = None) -> bool:
    """Kill-switch semantics: "0"/"false"/"no"/"off" (any case) are
    False, any other non-empty string is True, unset/empty keeps the
    default."""
    _register(name, "bool", default)
    v = _lookup(name, environ)
    if v is None or not str(v).strip():
        return default
    return str(v).strip().lower() not in _FALSY
