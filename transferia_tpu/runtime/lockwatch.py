"""Runtime lock-order sentinel (the dynamic half of LCK002).

`TRANSFERIA_TPU_LOCKWATCH=1` (or an explicit `arm()`) turns the named
production locks created through :func:`named_lock` into instrumented
wrappers that record, per thread, the stack of locks currently held.
Every first acquisition of lock B while lock A is held contributes the
edge ``A -> B`` to an observed global order DAG; acquiring A while B is
held after that is a **lock-order inversion** — the runtime witness of
a potential deadlock — and produces a structured finding carrying both
acquisition sites (the site that established ``A -> B`` and the site
that just observed ``B -> A``).

Also watched:

- **long holds** — a lock held beyond ``TRANSFERIA_TPU_LOCKWATCH_HOLD_MS``
  (default 250 ms) at release time;
- **blocking calls under a lock** — `time.sleep` is patched while armed
  (call sites that already route blocking work through helpers can call
  :func:`note_blocking` directly).

Cost model: locks created while the watch is DISARMED are plain
`threading` primitives — zero overhead.  A `WatchedLock` under an armed
watch pays one frame probe plus two dict updates per acquire/release
pair (single-digit microseconds); full stacks are captured only when a
finding fires.  Counters fold into `DeviceStats`
(`lockwatch_*` metrics) and ride obs segments so the chaos
``lock_order`` gauntlet and the fleet pane can assert "zero inversions"
across processes.

Leaf module: stdlib + `runtime.knobs` only.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Optional

from transferia_tpu.runtime import knobs

ENV_LOCKWATCH = "TRANSFERIA_TPU_LOCKWATCH"
ENV_HOLD_MS = "TRANSFERIA_TPU_LOCKWATCH_HOLD_MS"
DEFAULT_HOLD_MS = 250.0

# findings kept per watch (dedup usually keeps this tiny; the bound is
# a safety valve so a pathological schedule can't grow memory)
MAX_FINDINGS = 256
_OBS_FINDINGS = 32          # findings shipped per obs segment

COUNTER_NAMES = ("acquisitions", "inversions", "long_holds",
                 "blocking_in_lock")


def _site() -> str:
    """`file:line` of the production caller, skipping lockwatch frames."""
    try:
        f = sys._getframe(1)
        while f is not None and f.f_code.co_filename == __file__:
            f = f.f_back
        if f is None:
            return "?:0"
        return f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno}"
    except Exception:
        return "?:0"


def _stack(limit: int = 12) -> list:
    return [ln.strip() for ln in
            traceback.format_stack(limit=limit)[:-2]]


class _Held:
    """One entry of a thread's held-lock stack."""

    __slots__ = ("name", "t0", "site", "count")

    def __init__(self, name: str, t0: float, site: str):
        self.name = name
        self.t0 = t0
        self.site = site
        self.count = 1


class LockWatch:
    """The sentinel: observed order DAG + per-thread held stacks."""

    def __init__(self, hold_ms: Optional[float] = None):
        if hold_ms is None:
            hold_ms = knobs.env_float(ENV_HOLD_MS, DEFAULT_HOLD_MS)
        self.hold_ms = float(hold_ms)
        self._lock = threading.Lock()      # guards DAG/findings/counters
        self._tls = threading.local()
        # edge (a, b): first site pair that observed "b acquired while
        # a held" — the witness replayed when the inverse edge appears
        self._edges: dict = {}
        self._findings: list = []
        self._finding_keys: set = set()
        self._counters = dict.fromkeys(COUNTER_NAMES, 0)
        self._folded = dict.fromkeys(COUNTER_NAMES, 0)

    # -- per-thread stack ---------------------------------------------------
    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def held_names(self) -> list:
        return [h.name for h in self._held()]

    def _add_finding(self, key, finding: dict) -> None:
        # caller holds self._lock
        if key in self._finding_keys or \
                len(self._findings) >= MAX_FINDINGS:
            return
        self._finding_keys.add(key)
        self._findings.append(finding)

    # -- events ---------------------------------------------------------
    def note_acquire(self, name: str) -> None:
        held = self._held()
        for h in held:
            if h.name == name:           # reentrant (RLock) acquire
                h.count += 1
                return
        site = _site()
        entry = _Held(name, time.monotonic(), site)
        inversion = None
        with self._lock:
            self._counters["acquisitions"] += 1
            for h in held:
                fwd = (h.name, name)
                rev = (name, h.name)
                if rev in self._edges and fwd not in self._edges:
                    first = self._edges[rev]
                    key = ("inv",) + tuple(sorted((h.name, name)))
                    if key not in self._finding_keys:
                        inversion = (h, first, key, site)
                if fwd not in self._edges:
                    self._edges[fwd] = {"held_site": h.site,
                                        "acquire_site": site}
            if inversion is not None:
                h, first, key, site2 = inversion
                self._counters["inversions"] += 1
                self._add_finding(key, {
                    "kind": "lock_order_inversion",
                    "locks": sorted((h.name, name)),
                    "first": {"order": [name, h.name],
                              "held_site": first["held_site"],
                              "acquire_site": first["acquire_site"]},
                    "second": {"order": [h.name, name],
                               "held_site": h.site,
                               "acquire_site": site2},
                    "thread": threading.current_thread().name,
                    "stack": _stack(),
                })
        held.append(entry)

    def note_release(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            h = held[i]
            if h.name != name:
                continue
            h.count -= 1
            if h.count > 0:
                return
            held.pop(i)
            dt_ms = (time.monotonic() - h.t0) * 1000.0
            if dt_ms > self.hold_ms:
                with self._lock:
                    self._counters["long_holds"] += 1
                    self._add_finding(("hold", name, h.site), {
                        "kind": "long_hold",
                        "lock": name,
                        "held_ms": round(dt_ms, 3),
                        "threshold_ms": self.hold_ms,
                        "acquire_site": h.site,
                        "thread": threading.current_thread().name,
                    })
            return

    def note_blocking(self, label: str) -> None:
        """A blocking call ran on this thread; a finding if a watched
        lock is held (patched `time.sleep` lands here while armed)."""
        held = self._held()
        if not held:
            return
        top = held[-1]
        site = _site()
        with self._lock:
            self._counters["blocking_in_lock"] += 1
            self._add_finding(("blk", label, top.name, site), {
                "kind": "blocking_in_lock",
                "call": label,
                "lock": top.name,
                "locks_held": [h.name for h in held],
                "call_site": site,
                "acquire_site": top.site,
                "thread": threading.current_thread().name,
                "stack": _stack(),
            })

    # -- reporting ------------------------------------------------------
    def counters(self) -> dict:
        with self._lock:
            return dict(self._counters)

    def findings(self, kind: str = "") -> list:
        with self._lock:
            out = list(self._findings)
        if kind:
            out = [f for f in out if f.get("kind") == kind]
        return out

    def inversions(self) -> list:
        return self.findings("lock_order_inversion")

    def edge_count(self) -> int:
        with self._lock:
            return len(self._edges)

    def snapshot(self) -> dict:
        """Cumulative counters + a bounded findings list (obs segment
        payload: mergeable latest-per-process, like the ledger)."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "order_edges": len(self._edges),
                "findings": [dict(f, stack=None)
                             for f in self._findings[:_OBS_FINDINGS]],
            }

    def fold_into(self, metrics) -> dict:
        """Publish counter DELTAS since the last fold into a Metrics
        registry as `lockwatch_*` counters (idempotent when no new
        events arrived — fold twice, publish once)."""
        with self._lock:
            deltas = {name: self._counters[name] - self._folded[name]
                      for name in COUNTER_NAMES}
            self._folded = dict(self._counters)
        for name, d in deltas.items():
            if d:
                metrics.counter(f"lockwatch_{name}").inc(d)
        return deltas


class WatchedLock:
    """Instrumented wrapper over a `threading` lock.

    Implements the private Condition protocol (`_release_save` /
    `_acquire_restore` / `_is_owned`) so `threading.Condition(watched)`
    keeps working — a `cond.wait()` really releases the lock, and the
    held-stack bookkeeping must agree."""

    __slots__ = ("name", "_inner", "_reentrant")

    def __init__(self, name: str, inner, reentrant: bool):
        self.name = name
        self._inner = inner
        self._reentrant = reentrant

    def _watch(self) -> Optional["LockWatch"]:
        return _STATE

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            w = _STATE
            if w is not None:
                w.note_acquire(self.name)
        return got

    def release(self) -> None:
        w = _STATE
        if w is not None:
            w.note_release(self.name)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        return bool(locked()) if locked is not None else False

    # -- threading.Condition protocol ------------------------------------
    def _release_save(self):
        w = _STATE
        if w is not None:
            w.note_release(self.name)
        inner = self._inner
        if hasattr(inner, "_release_save"):
            return inner._release_save()
        inner.release()
        return None

    def _acquire_restore(self, state) -> None:
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(state)
        else:
            inner.acquire()
        w = _STATE
        if w is not None:
            w.note_acquire(self.name)

    def _is_owned(self) -> bool:
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        w = _STATE
        if w is not None:
            return self.name in w.held_names()
        # disarmed fallback mirrors Condition's own probe
        if inner.acquire(False):
            inner.release()
            return False
        return True


# -- module state ------------------------------------------------------------

_STATE: Optional[LockWatch] = None
_ARM_LOCK = threading.Lock()
_real_sleep = time.sleep


def _watched_sleep(seconds):
    w = _STATE
    if w is not None:
        w.note_blocking("time.sleep")
    return _real_sleep(seconds)


def is_armed() -> bool:
    return _STATE is not None


def active() -> Optional[LockWatch]:
    return _STATE


def arm(hold_ms: Optional[float] = None) -> LockWatch:
    """Install (or return) the process-wide watch and patch
    `time.sleep` for blocking-call detection."""
    global _STATE
    with _ARM_LOCK:
        if _STATE is None:
            _STATE = LockWatch(hold_ms=hold_ms)
            time.sleep = _watched_sleep
        return _STATE


def disarm() -> Optional[LockWatch]:
    """Remove the watch (returns it for post-mortem reads); locks
    created while armed fall back to plain delegation."""
    global _STATE
    with _ARM_LOCK:
        w = _STATE
        _STATE = None
        if time.sleep is _watched_sleep:
            time.sleep = _real_sleep
        return w


def named_lock(name: str, kind: str = "lock"):
    """A named lock that joins the watch when one is armed at creation
    time.  `kind`: "lock" | "rlock".  Disarmed processes get the plain
    primitive back — the hot path stays untouched."""
    reentrant = kind == "rlock"
    inner = threading.RLock() if reentrant else threading.Lock()
    if _STATE is None and not knobs.env_bool(ENV_LOCKWATCH, False):
        return inner
    if _STATE is None:
        arm()
    return WatchedLock(name, inner, reentrant)


def note_blocking(label: str) -> None:
    """Explicit hook for blocking helpers (socket reads, HTTP
    roundtrips) that want coverage beyond the `time.sleep` patch."""
    w = _STATE
    if w is not None:
        w.note_blocking(label)


def fold_into(metrics) -> dict:
    """Fold the active watch's counter deltas into `metrics`
    (`DeviceStats` exposes them as `lockwatch_*`); no-op disarmed."""
    w = _STATE
    if w is None:
        return {}
    return w.fold_into(metrics)
