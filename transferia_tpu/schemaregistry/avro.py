"""Minimal Avro binary decoder (schema-driven, dependency-free).

Covers what Confluent-wire Debezium/connector payloads use: records,
primitives, unions (the nullable-field idiom), enums, fixed, arrays, maps
and logical-type passthrough (decimal bytes stay bytes; timestamps stay
ints — the canonical typesystem maps them downstream).  The encoding is
the public Avro spec: zigzag-varint ints/longs, little-endian IEEE
float/double, length-prefixed bytes/strings, block-encoded arrays/maps.

Reference gap being closed: pkg/schemaregistry's Avro deserializer path —
round 1 routed Avro payloads to _unparsed with "unsupported".
"""

from __future__ import annotations

import json
import struct
from typing import Any


class AvroError(ValueError):
    pass


class Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def varint(self) -> int:
        result = shift = 0
        while True:
            if self.pos >= len(self.buf):
                raise AvroError("truncated varint")
            b = self.buf[self.pos]
            self.pos += 1
            result |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
            if shift > 70:
                raise AvroError("varint overflow")
        return (result >> 1) ^ -(result & 1)  # zigzag

    def take(self, n: int) -> bytes:
        if n < 0:
            raise AvroError("negative length")
        if self.pos + n > len(self.buf):
            raise AvroError("truncated data")
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out


class AvroSchema:
    """Parsed schema with named-type resolution (records/enums/fixed)."""

    def __init__(self, schema_json: str):
        self.named: dict[str, Any] = {}
        self.root = self._norm(json.loads(schema_json), "")

    def _norm(self, s, namespace: str):
        if isinstance(s, list):
            return ["union", [self._norm(x, namespace) for x in s]]
        if isinstance(s, str):
            return s  # primitive or named-type reference
        t = s.get("type")
        if t in ("record", "error"):
            ns = s.get("namespace", namespace)
            name = s["name"] if "." in s["name"] \
                else (f"{ns}.{s['name']}" if ns else s["name"])
            fields = []
            node = ["record", name, fields]
            self.named[name] = node
            self.named[s["name"]] = node  # short-name refs too
            for f in s.get("fields", []):
                fields.append((f["name"], self._norm(f["type"], ns)))
            return node
        if t in ("enum", "fixed"):
            node = (["enum", s.get("symbols", [])] if t == "enum"
                    else ["fixed", int(s.get("size", 0))])
            ns = s.get("namespace", namespace)
            self.named[s["name"]] = node
            if ns and "." not in s["name"]:
                # standard writers reference enums/fixed by fullname too
                self.named[f"{ns}.{s['name']}"] = node
            return node
        if t == "array":
            return ["array", self._norm(s.get("items", "null"), namespace)]
        if t == "map":
            return ["map", self._norm(s.get("values", "null"), namespace)]
        if isinstance(t, (dict, list)):
            return self._norm(t, namespace)
        return t  # {"type": "long", "logicalType": ...} etc.

    def decode(self, payload: bytes) -> Any:
        r = Reader(payload)
        out = self._read(self.root, r)
        return out

    def _read(self, node, r: Reader) -> Any:
        if isinstance(node, str):
            if node in ("null",):
                return None
            if node == "boolean":
                return r.take(1) != b"\x00"
            if node in ("int", "long"):
                return r.varint()
            if node == "float":
                return struct.unpack("<f", r.take(4))[0]
            if node == "double":
                return struct.unpack("<d", r.take(8))[0]
            if node == "bytes":
                return bytes(r.take(r.varint()))
            if node == "string":
                return r.take(r.varint()).decode("utf-8")
            resolved = self.named.get(node)
            if resolved is None:
                raise AvroError(f"unknown avro type {node!r}")
            return self._read(resolved, r)
        kind = node[0]
        if kind == "union":
            idx = r.varint()
            branches = node[1]
            if not 0 <= idx < len(branches):
                raise AvroError(f"union index {idx} out of range")
            return self._read(branches[idx], r)
        if kind == "record":
            return {name: self._read(t, r) for name, t in node[2]}
        if kind == "enum":
            idx = r.varint()
            symbols = node[1]
            if not 0 <= idx < len(symbols):
                raise AvroError(f"enum index {idx} out of range")
            return symbols[idx]
        if kind == "fixed":
            return bytes(r.take(node[1]))
        if kind == "array":
            out = []
            while True:
                n = r.varint()
                if n == 0:
                    return out
                if n < 0:
                    r.varint()  # block byte size (skippable)
                    n = -n
                for _ in range(n):
                    out.append(self._read(node[1], r))
        if kind == "map":
            out = {}
            while True:
                n = r.varint()
                if n == 0:
                    return out
                if n < 0:
                    r.varint()
                    n = -n
                for _ in range(n):
                    k = r.take(r.varint()).decode("utf-8")
                    out[k] = self._read(node[1], r)
        raise AvroError(f"unsupported avro node {node!r}")
