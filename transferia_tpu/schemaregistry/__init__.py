"""Confluent Schema Registry client (reference: pkg/schemaregistry/).

Resolves schema ids from the registry's REST API and adapts JSON-schema
definitions into the generic parser's field specs; plugs into the
confluent_schema_registry parser as its resolver.
"""

from transferia_tpu.schemaregistry.client import (
    SchemaRegistryClient,
    sr_resolver,
)

__all__ = ["SchemaRegistryClient", "sr_resolver"]
