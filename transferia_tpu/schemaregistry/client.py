"""Schema Registry REST client (stdlib HTTP)."""

from __future__ import annotations

import json
import logging
from typing import Optional

from transferia_tpu.abstract.errors import CategorizedError

logger = logging.getLogger(__name__)


class SRError(CategorizedError):
    pass


class SchemaRegistryClient:
    def __init__(self, url: str, user: str = "", password: str = "",
                 timeout: float = 30.0):
        import urllib.parse

        parsed = urllib.parse.urlparse(url)
        self.secure = parsed.scheme == "https"
        self.host = parsed.hostname or "localhost"
        self.port = parsed.port or (443 if self.secure else 8081)
        self.base = parsed.path.rstrip("/")
        self.user = user
        self.password = password
        self.timeout = timeout
        self._cache: dict[int, dict] = {}

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None) -> dict:
        import http.client

        cls = http.client.HTTPSConnection if self.secure \
            else http.client.HTTPConnection
        conn = cls(self.host, self.port, timeout=self.timeout)
        try:
            headers = {
                "Content-Type": "application/vnd.schemaregistry.v1+json",
            }
            if self.user:
                import base64

                cred = base64.b64encode(
                    f"{self.user}:{self.password}".encode()
                ).decode()
                headers["Authorization"] = f"Basic {cred}"
            payload = json.dumps(body).encode() if body is not None \
                else None
            conn.request(method, self.base + path, body=payload,
                         headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                raise SRError(
                    CategorizedError.SOURCE,
                    f"schema registry HTTP {resp.status}: {data[:200]!r}",
                )
            return json.loads(data)
        except (ConnectionError, OSError) as e:
            raise SRError(CategorizedError.SOURCE,
                          f"schema registry unreachable: {e}") from e
        finally:
            conn.close()

    def _get(self, path: str) -> dict:
        return self._request("GET", path)

    def register_schema(self, subject: str, schema: str,
                        schema_type: str = "JSON") -> int:
        """POST /subjects/<subject>/versions -> schema id (idempotent on
        the registry side for identical schemas)."""
        out = self._request(
            "POST", f"/subjects/{subject}/versions",
            {"schema": schema, "schemaType": schema_type},
        )
        return int(out["id"])

    def schema_by_id(self, schema_id: int) -> dict:
        """Raw registry entry: {"schema": "...", "schemaType": "JSON"|...}"""
        if schema_id not in self._cache:
            self._cache[schema_id] = self._get(f"/schemas/ids/{schema_id}")
        return self._cache[schema_id]

    def fields_for(self, schema_id: int) -> Optional[list[dict]]:
        """Generic-parser field specs from a JSON-schema entry; None for
        schema types we can't map (avro/protobuf) — the parser then falls
        back to inference or _unparsed routing."""
        entry = self.schema_by_id(schema_id)
        if entry.get("schemaType", "AVRO") not in ("JSON",):
            logger.warning(
                "schema id %d is %s; JSON-schema only — falling back to "
                "inference", schema_id, entry.get("schemaType"),
            )
            return None
        try:
            schema = json.loads(entry["schema"])
        except (KeyError, ValueError):
            return None
        props = schema.get("properties")
        if not isinstance(props, dict):
            return None
        required = set(schema.get("required") or [])
        type_map = {
            "integer": "int64", "number": "double", "string": "utf8",
            "boolean": "boolean",
        }
        return [
            {
                "name": name,
                "type": type_map.get(
                    spec.get("type") if isinstance(spec, dict) else "",
                    "any",
                ),
                "required": name in required,
            }
            for name, spec in props.items()
        ]


def sr_resolver(url: str, **kw):
    """Resolver factory for the confluent_schema_registry parser config.
    The underlying client is exposed as `.client` so the parser's Avro
    path reuses the same connection/config and per-id cache."""
    client = SchemaRegistryClient(url, **kw)

    def resolve(schema_id: int):
        return client.fields_for(schema_id)

    resolve.client = client
    return resolve
