"""Ad-hoc table upload (pkg/worker/tasks/upload_tables.go:58)."""

from __future__ import annotations

from typing import Optional

from transferia_tpu.abstract.schema import TableID
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.coordinator.interface import Coordinator
from transferia_tpu.stats.registry import Metrics
from transferia_tpu.tasks.snapshot import SnapshotLoader


def upload(transfer, coordinator: Coordinator,
           tables: list[str],
           metrics: Optional[Metrics] = None,
           operation_id: Optional[str] = None) -> None:
    """Upload an explicit table list (no incremental-state update,
    upload_tables.go:58)."""
    if not tables:
        raise ValueError("upload: explicit table list required")
    descriptions = [
        TableDescription(id=TableID.parse(t)) for t in tables
    ]
    loader = SnapshotLoader(transfer, coordinator, metrics=metrics,
                            operation_id=operation_id)
    loader.upload_tables(descriptions)
