"""Event-model-v2 snapshot upload (load_snapshot_v2.go:139 UploadV2).

Drives an a2 SnapshotProvider part by part into an EventTarget: the
destination's native a2 target when it has one (e.g. ClickHouse), else
any v1 sink pipeline bridged through EventTargetOverAsyncSink — so the
full middleware stack (transformers, bufferer, retries, stats) applies
to a2 flows too.
"""

from __future__ import annotations

import logging
from typing import Optional

from transferia_tpu.abstract.kinds import Kind
from transferia_tpu.coordinator.interface import Coordinator
from transferia_tpu.events.model import TableLoadEvent
from transferia_tpu.events.pipeline import (
    EventTarget,
    EventTargetOverAsyncSink,
    SnapshotProvider,
)
from transferia_tpu.stats.registry import Metrics

logger = logging.getLogger(__name__)


def make_event_target(transfer, metrics: Optional[Metrics] = None
                      ) -> EventTarget:
    """Native a2 target when the destination has one AND the transfer
    carries no transformation chain — a native target writes events
    directly, so a configured transformer must route through the full v1
    middleware stack behind the bridge instead of being silently skipped.
    The bridged sink is built at snapshot stage (retries + dedicated
    snapshot sinkers), matching the v1 loader."""
    from transferia_tpu.factories import make_async_sink
    from transferia_tpu.providers.registry import get_provider

    dst_provider = get_provider(transfer.dst_provider(), transfer, metrics)
    if not transfer.transformation:
        native = dst_provider.event_target()
        if native is not None:
            logger.info("a2 upload: native %s event target",
                        transfer.dst_provider())
            return native
    return EventTargetOverAsyncSink(
        make_async_sink(transfer, metrics, snapshot_stage=True))


def upload_v2(transfer, coordinator: Coordinator,
              provider: SnapshotProvider,
              metrics: Optional[Metrics] = None) -> int:
    """Snapshot every data-object part through typed events; returns rows
    moved.  Control brackets (Init/Done TableLoadEvents) frame each part
    the way the v1 loader frames Storage loads."""
    metrics = metrics or Metrics()
    provider.init()
    provider.begin_snapshot()
    total_rows = 0
    target = make_event_target(transfer, metrics)
    try:
        include = transfer.include_ids() or None
        objects = provider.data_objects(include)
        if not objects:
            raise ValueError(
                "a2 snapshot: no data objects match the include list")
        for tid, parts in objects.items():
            schema = provider.table_schema(parts[0]) if parts else None
            target.async_push([TableLoadEvent(
                tid, Kind.INIT_TABLE_LOAD, schema=schema)]).result()
            for part in parts:
                source = provider.create_snapshot_source(part)
                source.start(target)
                progress = source.progress()
                if not progress.done:
                    raise RuntimeError(
                        f"a2 snapshot source for {part} stopped at "
                        f"{progress.current}/{progress.total}")
                total_rows += progress.current
                logger.info("a2 part %s: %d rows", part.part_key or tid,
                            progress.current)
            target.async_push([TableLoadEvent(
                tid, Kind.DONE_TABLE_LOAD, schema=schema)]).result()
        provider.end_snapshot()
    finally:
        target.close()
        provider.close()
    return total_rows
