"""SnapshotLoader: the snapshot engine.

Reference parity: pkg/worker/tasks/load_snapshot.go — single-worker (:383),
sharded main (:495) and sharded secondary (:607) modes; the DoUploadTables
hot loop (:893-1098) with a ProcessCount-bounded worker pool, per-part sink
pipelines, Init/DoneTableLoad control events bracketing Storage.LoadTable,
x3 exponential-backoff part retry, and coordinator progress flushes.

Differences by design: parts stream columnar blocks; per-part sinks come
from the factory with snapshot-stage retries enabled; part claims go through
Coordinator.assign_operation_part for both local and sharded modes (the
in-memory coordinator doubles as the local queue, replacing the reference's
BuildTPP local/remote split).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

from transferia_tpu.abstract.change_item import (
    done_sharded_table_load,
    done_table_load,
    init_sharded_table_load,
    init_table_load,
)
from transferia_tpu.abstract.commit import find_staged_sink
from transferia_tpu.abstract.errors import (
    CodedError,
    Codes,
    StaleEpochPublishError,
    TableUploadError,
    TransferPreemptedError,
    WorkerKilledError,
    is_preemption,
    is_retriable,
)
from transferia_tpu.abstract.interfaces import (
    AsyncPartDiscovery,
    IncrementalStorage,
    IncrementalTable,
    PositionalStorage,
    ShardedStateStorage,
    SnapshotableStorage,
    Storage,
    resolve_all,
)
from transferia_tpu.abstract.schema import TableID
from transferia_tpu.abstract.table import OperationTablePart, TableDescription
from transferia_tpu.chaos.failpoints import failpoint

from transferia_tpu.coordinator.interface import (
    Coordinator,
    env_float,
    lease_expired,
)
from transferia_tpu.runtime import knobs
from transferia_tpu.factories import make_async_sink, new_storage
from transferia_tpu.stats import fleetobs, trace
from transferia_tpu.stats.ledger import LEDGER
from transferia_tpu.stats.registry import (
    CommitStats,
    LeaseStats,
    Metrics,
    TableStats,
)
from transferia_tpu.tasks.table_splitter import split_tables
from transferia_tpu.utils.backoff import retry_with_backoff

logger = logging.getLogger(__name__)

PART_RETRIES = 3  # load_snapshot.go:1070-1086
# per-part retry backoff base (chaos trials shrink this: the retry
# schedule is under test there, not the sleep lengths)
PART_RETRY_BASE_DELAY = 1.0

# Staged two-phase sink commits (abstract/commit.py): on by default
# wherever both the sink and the coordinator are capable; "off"/"0"
# forces every sink back to the at-least-once path.
ENV_STAGED_COMMIT = "TRANSFERIA_TPU_STAGED_COMMIT"


def staged_commits_enabled(environ=os.environ) -> bool:
    return knobs.env_str(ENV_STAGED_COMMIT, "auto",
                         environ=environ).lower() not in (
        "off", "0", "false", "no")


@dataclass
class SnapshotTuning:
    """Deadline/poll knobs formerly hardcoded in the engine.  Chaos
    trials shrink these the same way they shrink PART_RETRY_BASE_DELAY
    (the schedules are under test, not the production sleep lengths);
    operators override via environment."""

    # secondary waiting for the main to publish the part queue
    secondary_bootstrap_timeout: float = 600.0
    # main's join loop over secondaries draining the queue
    wait_poll: float = 0.5
    wait_timeout: float = 24 * 3600.0
    # fail-fast window: no progress AND no live lease for this long
    # means every worker holding work is dead and nobody is reclaiming
    stall_timeout: float = 600.0
    # lease-renewal heartbeat period (leases themselves are coordinator
    # TTLs: coordinator/interface.py DEFAULT_LEASE_SECONDS)
    heartbeat_interval: float = 5.0

    @classmethod
    def from_env(cls, environ=os.environ) -> "SnapshotTuning":
        return cls(
            secondary_bootstrap_timeout=env_float(
                environ, "TRANSFERIA_TPU_SNAPSHOT_BOOTSTRAP_TIMEOUT",
                600.0),
            wait_poll=env_float(
                environ, "TRANSFERIA_TPU_SNAPSHOT_WAIT_POLL", 0.5),
            wait_timeout=env_float(
                environ, "TRANSFERIA_TPU_SNAPSHOT_WAIT_TIMEOUT",
                24 * 3600.0),
            stall_timeout=env_float(
                environ, "TRANSFERIA_TPU_SNAPSHOT_STALL_TIMEOUT", 600.0),
            heartbeat_interval=env_float(
                environ, "TRANSFERIA_TPU_HEARTBEAT_INTERVAL", 5.0),
        )


TUNING = SnapshotTuning.from_env()


class SnapshotLoader:
    def __init__(self, transfer, coordinator: Coordinator,
                 operation_id: Optional[str] = None,
                 metrics: Optional[Metrics] = None,
                 preempted: "Optional[Callable[[], bool]]" = None,
                 resume: bool = False):
        self.transfer = transfer
        self.cp = coordinator
        # fleet preemption probe (fleet/worker.py): polled between
        # parts; True = stop claiming and raise TransferPreemptedError
        # — the committed parts stay, the transfer resumes elsewhere
        self._preempted = preempted
        # resume a previous attempt's operation: reuse an existing part
        # queue instead of recreating it (recreating would reset the
        # completed flags and replay the whole snapshot)
        self.resume = resume
        # Deterministic default: sharded workers in separate processes must
        # agree on the operation id without a side channel (the reference
        # passes it via the k8s job spec; trtpu can override with
        # --operation-id).
        self.operation_id = operation_id or f"op-{transfer.id}"
        self.metrics = metrics or Metrics()
        self.table_stats = TableStats(self.metrics)
        self.lease_stats = LeaseStats(self.metrics)
        self.commit_stats = CommitStats(self.metrics)
        # staged two-phase commits need a coordinator that can fence
        # the publish decision; the sink side is probed per part
        self._staged_commits = staged_commits_enabled() and \
            coordinator.supports_staged_commits()
        self.worker_index = transfer.runtime.current_job
        self.process_count = max(1, transfer.runtime.sharding.process_count)
        self.is_main = transfer.runtime.is_main
        self._progress_lock = threading.Lock()
        # heartbeat-visible progress (folded into operation_health)
        self._phase = "init"
        self._local_parts_done = 0
        self._local_rows_done = 0
        # tables whose scan predicate has been computed (set-once; reads
        # and adds race benignly — worst case one repeat computation)
        self._pushdown_done: set = set()
        # fleet observability export stream (stats/fleetobs.py): under
        # a fleet worker this joins the worker's ambient stream; a bare
        # sharded loader exports under its own worker label.  Disabled
        # (no-op) on coordinators without obs-segment support.
        self._obs = fleetobs.exporter_for(
            coordinator, worker=f"snap.w{self.worker_index}."
                                f"{os.getpid()}")

    # -- entry points ---------------------------------------------------------
    def upload_tables(self, tables: Optional[list[TableDescription]] = None
                      ) -> None:
        """UploadTables (load_snapshot.go:346): snapshot the given tables
        (None = all tables passing the transfer's include filter)."""
        storage = new_storage(self.transfer, self.metrics)
        # the operation root: every part/batch/device span of this
        # snapshot nests (or flows, across worker threads) under it,
        # and every resource event bills this transfer in the ledger
        # (tenant inherited from an enclosing fleet lane scope)
        op_sp = trace.span("snapshot_op", transfer_id=self.transfer.id,
                           operation_id=self.operation_id,
                           worker=self.worker_index)
        try:
            with op_sp, LEDGER.context(transfer_id=self.transfer.id):
                if tables is None:
                    tables = self.filtered_table_list(storage)
                if self.is_main:
                    self._main_flow(storage, tables)
                else:
                    self._secondary_flow(storage)
        finally:
            storage.close()
            # final observability flush: whatever this operation spent
            # survives the process even if it exits right after
            self._obs.export("final")

    def filtered_table_list(self, storage: Storage
                            ) -> list[TableDescription]:
        """model.FilteredTableList: apply the transfer's include-list."""
        include = self.transfer.include_ids() or None
        infos = storage.table_list(include)
        out = [
            TableDescription(id=tid, eta_rows=info.eta_rows)
            for tid, info in infos.items()
        ]
        out.sort(key=lambda t: -t.eta_rows)
        return out

    # -- incremental cursors (load_snapshot_incremental.go) -----------------
    def _incremental_tables(self) -> list[IncrementalTable]:
        return [
            IncrementalTable(TableID(c.namespace, c.name), c.cursor_field,
                             c.initial_state)
            for c in self.transfer.regular_snapshot.incremental
        ]

    def _apply_incremental(self, storage: Storage,
                           tables: list[TableDescription]
                           ) -> tuple[list[TableDescription], Optional[dict]]:
        inc = self._incremental_tables()
        if not inc or not isinstance(storage, IncrementalStorage):
            return tables, None
        # capture the next cursor BEFORE loading: rows arriving during the
        # snapshot re-read next time instead of being skipped
        next_state = storage.next_increment_state(inc)
        state = self.cp.get_transfer_state(self.transfer.id).get(
            "incremental_state", {}
        )
        filtered = {td.id: td for td in
                    storage.get_increment_state(inc, state)}
        merged = [filtered.get(td.id, td) for td in tables]
        return merged, next_state

    # -- main worker ----------------------------------------------------------
    def _main_flow(self, storage: Storage,
                   tables: list[TableDescription]) -> None:
        if isinstance(storage, SnapshotableStorage):
            storage.begin_snapshot()
        try:
            if isinstance(storage, PositionalStorage):
                pos = storage.position()
                if pos:
                    self.cp.set_transfer_state(
                        self.transfer.id, {"snapshot_position": pos}
                    )
            tables, next_inc_state = self._apply_incremental(storage, tables)
            # main-worker restart detection (load_snapshot.go:496-501):
            # an INCOMPLETE queue means a previous main crashed mid-
            # operation with secondaries possibly still attached.  A fully
            # completed queue is just the previous successful activation —
            # recreate and run (re-activation must not wedge).  Under
            # `resume` (fleet re-claim after a crash reclaim or a
            # preemption revoke) an existing queue is instead REUSED:
            # the committed parts are the checkpoint the transfer
            # resumes from, recreating would replay the whole snapshot.
            existing = self.cp.operation_parts(self.operation_id) \
                if (self.job_count() > 1 or self.resume) else []
            resume_queue = bool(self.resume and existing)
            if existing and not resume_queue \
                    and not all(p.completed for p in existing):
                raise CodedError(
                    Codes.MAIN_WORKER_RESTART,
                    f"operation {self.operation_id} has incomplete parts: "
                    f"the main worker restarted mid-operation",
                )
            if isinstance(storage, ShardedStateStorage) and \
                    self.job_count() > 1:
                # consistent-point handoff to secondaries' storages
                self.cp.set_operation_state(self.operation_id, {
                    "sharded_state": storage.sharded_state(),
                })
            if resume_queue:
                # resume: the queue (and its completed flags) IS the
                # checkpoint.  Release any claims a previous attempt of
                # THIS worker index left leased (a zombie's leases; its
                # later updates are epoch-fenced), then upload whatever
                # is incomplete — nothing assignable means the previous
                # attempt finished everything and only publication
                # remained.
                released = self.cp.clear_assigned_parts(
                    self.operation_id, self.worker_index)
                trace.instant("snapshot_resume",
                              operation_id=self.operation_id,
                              parts=len(existing),
                              completed=sum(1 for p in existing
                                            if p.completed),
                              released=released)
                logger.info(
                    "resuming operation %s: %d/%d part(s) already "
                    "committed (%d stale claim(s) released)",
                    self.operation_id,
                    sum(1 for p in existing if p.completed),
                    len(existing), released)
                self.cp.set_operation_state(
                    self.operation_id, {"parts_discovery_done": True})
                discovery = None
                multi_part = {
                    p.table_id for p in existing if p.parts_count > 1
                }
                # init brackets were sent by the FIRST attempt and are
                # not re-sent on resume; everything else is the shared
                # publish tail
                self._upload_publish_tail(
                    storage, tables, multi_part, discovery,
                    next_inc_state, send_init=False)
                return
            # a fresh run must reset the discovery flag (a re-activation
            # would otherwise see the previous run's True and drain early)
            self.cp.set_operation_state(self.operation_id,
                                        {"parts_discovery_done": False})
            discovery = None
            if isinstance(storage, AsyncPartDiscovery):
                # reset the queue (re-activation leftovers) before parts
                # stream in via add_operation_parts
                self.cp.create_operation_parts(self.operation_id, [])
                discovery = self._start_async_discovery(storage, tables)
                multi_part = {td.id for td in tables}
            else:
                parts = split_tables(storage, tables, self.transfer,
                                     self.operation_id)
                self.cp.create_operation_parts(self.operation_id, parts)
                self.cp.set_operation_state(self.operation_id,
                                            {"parts_discovery_done": True})
                self.table_stats.total_parts.set(len(parts))
                self.table_stats.eta_rows.set(
                    sum(p.eta_rows for p in parts))
                multi_part = {
                    p.table_id for p in parts if p.parts_count > 1
                }
            self._upload_publish_tail(storage, tables, multi_part,
                                      discovery, next_inc_state,
                                      send_init=True)
        finally:
            if isinstance(storage, SnapshotableStorage):
                storage.end_snapshot()

    def _upload_publish_tail(self, storage: Storage, tables,
                             multi_part: set, discovery,
                             next_inc_state, send_init: bool) -> None:
        """The shared back half of a snapshot run — upload, sharded
        join, done-brackets, incremental cursors, fingerprints — used
        by BOTH the fresh path and the fleet resume path so a change
        here can never silently apply to one and not the other.
        `send_init=False` on resume: the first attempt already sent
        the init brackets, and re-sending could reset sink-side
        sharded-table state."""
        schemas = {td.id: storage.table_schema(td.id) for td in tables}
        sink = make_async_sink(self.transfer, self.metrics,
                               snapshot_stage=True)
        try:
            if send_init:
                # sharded-table brackets (load_snapshot.go:821)
                futs = [
                    sink.async_push([init_sharded_table_load(
                        tid, schemas.get(tid))])
                    for tid in multi_part
                ]
                resolve_all(futs)
            self._do_upload_tables(storage, schemas)
            if discovery is not None:
                discovery.join()
                if self._discovery_error:
                    raise self._discovery_error
            if self.job_count() > 1:
                self._wait_all_parts_done()
            futs = [
                sink.async_push([done_sharded_table_load(
                    tid, schemas.get(tid))])
                for tid in multi_part
            ]
            resolve_all(futs)
        finally:
            sink.close()
        if next_inc_state is not None:
            # persist cursors only after the whole snapshot succeeded
            # (load_snapshot.go:228-240)
            self.cp.set_transfer_state(
                self.transfer.id,
                {"incremental_state": next_inc_state},
            )
        self._publish_fingerprints()

    def _publish_fingerprints(self) -> None:
        """Merge per-part fingerprints into per-table snapshot digests
        (order-independent, so shard/batch ordering is irrelevant) and
        record them in the operation state — the content address of what
        this snapshot wrote, comparable later by `trtpu checksum
        --method fingerprint` without re-reading the source."""
        if not self.transfer.fingerprint_validation():
            return
        from transferia_tpu.ops.rowhash import FingerprintAggregate

        import json as _json

        per_table: dict[str, FingerprintAggregate] = {}
        for part in self.cp.operation_parts(self.operation_id):
            if not part.fingerprint:
                continue
            if part.fingerprint.startswith("{"):
                # JSON mapping of output-table fqtn -> digest (renaming /
                # fan-out chains); compact form implies output == source
                try:
                    mapping = _json.loads(part.fingerprint)
                except ValueError:
                    logger.warning(
                        "part %s carries a malformed fingerprint map",
                        part.key())
                    continue
            else:
                mapping = {part.table_id.fqtn(): part.fingerprint}
            for fqtn, dg in mapping.items():
                agg = per_table.setdefault(fqtn, FingerprintAggregate())
                try:
                    agg.merge(FingerprintAggregate.parse(dg))
                except ValueError:
                    logger.warning(
                        "part %s carries a malformed fingerprint",
                        part.key())
        if not per_table:
            return
        digests = {t: a.digest() for t, a in per_table.items()}
        self.cp.set_operation_state(self.operation_id,
                                    {"table_fingerprints": digests})
        for t, d in sorted(digests.items()):
            logger.info("snapshot fingerprint %s: %s", t, d)

    def job_count(self) -> int:
        return max(1, self.transfer.runtime.sharding.job_count)

    # -- async part discovery (tpp_setter_async.go) -------------------------
    def _start_async_discovery(self, storage: AsyncPartDiscovery,
                               tables: list[TableDescription]
                               ) -> threading.Thread:
        """Publish parts concurrently with upload: huge table/object lists
        must not serialize activation.  Upload workers spin on the part
        queue until parts_discovery_done flips."""
        self._discovery_error: Optional[BaseException] = None

        def discover():
            total = 0
            eta = 0
            try:
                for td in tables:
                    batch: list[OperationTablePart] = []
                    last_flush = time.monotonic()
                    for part_td in storage.iter_table_parts(td):
                        batch.append(OperationTablePart(
                            operation_id=self.operation_id,
                            table_id=td.id,
                            part_index=total,
                            parts_count=0,  # unknown until drained
                            eta_rows=part_td.eta_rows,
                            filter=part_td.filter,
                        ))
                        total += 1
                        eta += part_td.eta_rows
                        # flush by count OR age: workers must see parts
                        # promptly even when discovery trickles
                        if len(batch) >= 64 or \
                                time.monotonic() - last_flush > 0.1:
                            self.cp.add_operation_parts(
                                self.operation_id, batch)
                            batch = []
                            last_flush = time.monotonic()
                    if batch:
                        self.cp.add_operation_parts(self.operation_id,
                                                    batch)
                self.table_stats.total_parts.set(total)
                self.table_stats.eta_rows.set(eta)
                logger.info("async discovery: %d parts published", total)
            except BaseException as e:  # propagate into the main flow
                self._discovery_error = e
            finally:
                self.cp.set_operation_state(
                    self.operation_id, {"parts_discovery_done": True})

        t = threading.Thread(target=discover, name="part-discovery",
                             daemon=True)
        t.start()
        return t

    def _discovery_open(self) -> bool:
        return not self.cp.get_operation_state(self.operation_id).get(
            "parts_discovery_done")

    def _wait_all_parts_done(self, poll: Optional[float] = None,
                             timeout: Optional[float] = None) -> None:
        """Main worker waits for secondaries to drain the queue
        (load_snapshot.go sharded main join).

        Lease-aware: instead of spinning silently for the full timeout,
        the loop watches part leases and progress.  While any pending
        part carries a live lease (or progress advances) somebody is
        alive and working — keep waiting.  When nothing has a live lease
        and nothing changes for `stall_timeout`, every worker holding
        work is dead and nobody reclaimed: fail fast with a diagnostic
        naming the orphaned parts and their last-seen workers."""
        poll = TUNING.wait_poll if poll is None else poll
        timeout = TUNING.wait_timeout if timeout is None else timeout
        self._phase = "waiting"
        deadline = time.monotonic() + timeout
        last_sig = None
        last_change = time.monotonic()
        while time.monotonic() < deadline:
            parts = self.cp.operation_parts(self.operation_id)
            pending = [p for p in parts if not p.completed]
            if not pending and (parts or not self._discovery_open()):
                return
            now = time.time()
            sig = (
                len(parts),
                sum(1 for p in parts if p.completed),
                sum(p.completed_rows for p in parts),
                sum(p.assignment_epoch for p in parts),
                max((p.lease_expires_at for p in pending), default=0.0),
            )
            if sig != last_sig:
                last_sig = sig
                last_change = time.monotonic()
            # a claim without a lease deadline (legacy backend) gives no
            # liveness signal — treat it as live, never fail fast on it
            live = [p for p in pending
                    if p.worker_index is not None
                    and not lease_expired(p, now)]
            # fail fast only for a fleet that WAS here and died: some
            # part must have been claimed at least once.  An entirely
            # unclaimed queue means secondaries are merely slow to
            # arrive (pod pending, image pull) — keep waiting.
            claimed_ever = any(p.assignment_epoch > 0 for p in pending)
            stalled = time.monotonic() - last_change
            if not live and claimed_ever and \
                    stalled > TUNING.stall_timeout:
                raise CodedError(
                    Codes.SNAPSHOT_PARTS_ORPHANED,
                    self._orphan_diagnostic(pending, now, stalled),
                )
            self.cp.operation_health(self.operation_id, self.worker_index,
                                     {"phase": "waiting",
                                      "pending_parts": len(pending)})
            time.sleep(poll)
        raise TimeoutError(
            f"operation {self.operation_id}: parts not drained in time"
        )

    def _orphan_diagnostic(self, pending: list[OperationTablePart],
                           now: float, stalled: float) -> str:
        """Name each orphaned part, its last-seen worker, and that
        worker's last heartbeat — the on-call page for a dead fleet."""
        health = {}
        try:
            health = self.cp.get_operation_health(self.operation_id)
        except Exception:  # diagnostics must not mask the failure
            logger.exception("operation health read failed")
        lines = []
        for p in sorted(pending, key=lambda p: p.key()):
            holder = p.worker_index if p.worker_index is not None \
                else p.stolen_from
            if holder is None:
                lines.append(f"{p.key()}: never claimed")
                continue
            age = now - p.lease_expires_at if p.lease_expires_at > 0 \
                else None
            rep = health.get(holder) or {}
            beat = rep.get("ts")
            lines.append(
                f"{p.key()}: last seen on worker {holder}"
                + (f", lease expired {age:.1f}s ago" if age is not None
                   else ", no lease")
                + (f", last heartbeat {now - beat:.1f}s ago"
                   if beat else ", no heartbeat on record"))
        return (
            f"operation {self.operation_id}: {len(lines)} part(s) "
            f"orphaned — no live lease and no progress for "
            f"{stalled:.1f}s, and no surviving worker reclaimed them: "
            + "; ".join(lines)
        )

    # -- secondary worker -------------------------------------------------------
    def _secondary_flow(self, storage: Storage) -> None:
        """Sharded secondary (load_snapshot.go:607): wait for the part queue,
        apply the main's sharded source state, clear stale
        self-assignments (restart recovery), pull and upload."""
        self._phase = "bootstrap"
        deadline = time.monotonic() + TUNING.secondary_bootstrap_timeout
        while not self.cp.operation_parts(self.operation_id):
            if self.cp.get_operation_state(self.operation_id).get(
                    "parts_discovery_done"):
                # async discovery legitimately found zero parts: nothing
                # to upload — exit cleanly alongside the main worker
                logger.info("secondary %d: discovery done with empty "
                            "part queue", self.worker_index)
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"operation {self.operation_id}: main worker never "
                    f"published parts"
                )
            time.sleep(0.2)
        if isinstance(storage, ShardedStateStorage):
            state = self.cp.get_operation_state(self.operation_id).get(
                "sharded_state")
            if state is not None:
                # read from the main's consistent point
                # (SetShardedStateToSource, load_snapshot.go:607-671)
                storage.set_sharded_state(state)
        released = self.cp.clear_assigned_parts(self.operation_id,
                                                self.worker_index)
        if released:
            logger.info("secondary %d: released %d stale parts after restart",
                        self.worker_index, released)
        schemas: dict[TableID, object] = {}
        self._do_upload_tables(storage, schemas)

    # -- the hot loop -------------------------------------------------------
    def _setup_scan_pushdown(self, storage: Storage,
                             schemas: dict) -> None:
        """Push the chain's leading row filter into the scan when the
        storage supports it (ScanPredicateStorage).  Advisory: the chain
        re-applies the predicate, so a storage that ignores or only
        partially applies it stays correct — this just avoids decoding,
        pivoting, and transforming rows that are about to be dropped."""
        for tid, schema in schemas.items():
            self._push_scan_predicate(storage, tid, schema)

    def _push_scan_predicate(self, storage: Storage, tid,
                             schema) -> None:
        """Install the pushable predicate for one table (set-once; also
        the lazy path for secondary workers, whose schemas dict starts
        empty and fills as parts arrive in _upload_part)."""
        from transferia_tpu.abstract.interfaces import (
            ScanPredicateStorage,
        )

        if not isinstance(storage, ScanPredicateStorage):
            return
        if tid in self._pushdown_done:
            return
        self._pushdown_done.add(tid)
        from transferia_tpu.transform.chain import build_chain

        chain = build_chain(self.transfer.transformation)
        if chain is None or schema is None:
            return
        try:
            node = chain.pushable_predicate(tid, schema)
        except Exception:
            return
        if node is not None and storage.set_scan_predicate(tid, node):
            logger.info("scan pushdown for %s: %s", tid, node)

    # -- worker liveness: lease-renewal heartbeat ---------------------------
    def _heartbeat_loop(self, stop: threading.Event) -> None:
        """Renew this worker's part leases and fold phase/progress into
        the coordinator's operation_health reports.  Transient renewal
        failures are tolerated (the lease TTL absorbs several missed
        beats); a WorkerKilledError kills the heartbeat — the worker is
        then a zombie whose leases expire and get reclaimed."""
        while not stop.wait(TUNING.heartbeat_interval):
            try:
                failpoint("snapshot.lease_renew")
                sp = trace.span("lease_renew", worker=self.worker_index)
                with sp:
                    renewed = self.cp.renew_lease(self.operation_id,
                                                  self.worker_index)
                if sp:
                    sp.add(renewed=renewed)
                self.lease_stats.renewals.inc(renewed)
                with self._progress_lock:
                    payload = {
                        "phase": self._phase,
                        "parts_done": self._local_parts_done,
                        "rows": self._local_rows_done,
                        "leases": renewed,
                    }
                self.cp.operation_health(self.operation_id,
                                         self.worker_index, payload)
                # observability export at heartbeat cadence: a SIGKILL
                # between beats loses at most one export interval
                self._obs.export("periodic")
            except WorkerKilledError:
                logger.error(
                    "worker %d heartbeat killed: lease renewals stop, "
                    "parts will be reclaimed after expiry",
                    self.worker_index)
                return
            except Exception as e:
                self.lease_stats.heartbeat_failures.inc()
                logger.warning("worker %d heartbeat failed "
                               "(lease TTL absorbs it): %s",
                               self.worker_index, e)

    def _do_upload_tables(self, storage: Storage,
                          schemas: dict) -> None:
        """DoUploadTables (load_snapshot.go:893): ProcessCount workers pull
        parts from the coordinator until the queue drains.  A claim is a
        lease: drained workers linger while other workers hold live
        leases and reclaim their parts if the leases expire."""
        self._setup_scan_pushdown(storage, schemas)
        self._phase = "uploading"
        errors: list[BaseException] = []
        err_lock = threading.Lock()

        discovery_done = [False]  # latched: the flag never reverts

        def linger_wait() -> bool:
            """Nothing assignable right now.  True = keep looping (other
            workers hold live leases — they may die and their parts
            become stealable), False = queue genuinely done for us."""
            pending = [p for p in
                       self.cp.operation_parts(self.operation_id)
                       if not p.completed]
            if not pending:
                return False
            if all(p.worker_index == self.worker_index
                   for p in pending):
                # held by this worker's own sibling threads: they will
                # finish or error (an error stops every thread above)
                return False
            now = time.time()
            expiries = [p.lease_expires_at - now for p in pending
                        if p.lease_expires_at > 0]
            if not expiries:
                if any(p.worker_index is None for p in pending):
                    # assign race (e.g. a concurrent clear): the part
                    # is claimable on the next pass
                    time.sleep(0.05)
                    return True
                # lease-less claims (lease_seconds=0 legacy mode) never
                # expire — there is nothing to reclaim, so exit as the
                # pre-lease engine did instead of polling forever
                return False
            wait = min(expiries)
            time.sleep(min(1.0, max(0.05, wait)))
            return True

        # causal hop: upload worker threads (and the heartbeat) adopt
        # the submitting scope, so part spans parent to the operation
        # span — and, under a fleet lane, to the ticket trace — and
        # their resource events bill the right (transfer, tenant)
        op_ctx = trace.current_context()
        op_lkey = LEDGER.current_key()

        def worker():
            with trace.adopted(op_ctx), LEDGER.adopted(op_lkey):
                worker_loop()

        def worker_loop():
            idle_sleep = 0.05
            while True:
                with err_lock:
                    if errors:
                        return
                # part-boundary preemption (fleet lease revocation /
                # graceful drain): stop claiming BEFORE the next part —
                # the parts already committed are the resume point, and
                # a sibling thread mid-part finishes its part first
                # (work done is never thrown away)
                if self._preempted is not None and self._preempted():
                    with self._progress_lock:
                        done = self._local_parts_done
                    trace.instant("snapshot_preempt_yield",
                                  operation_id=self.operation_id,
                                  parts_done=done)
                    with err_lock:
                        errors.append(TransferPreemptedError(
                            f"transfer {self.transfer.id} yielded at a "
                            f"part boundary ({done} part(s) committed "
                            f"by this worker)"))
                    return
                part = self.cp.assign_operation_part(
                    self.operation_id, self.worker_index
                )
                if part is None:
                    if not discovery_done[0]:
                        if not self._discovery_open():
                            discovery_done[0] = True
                            continue  # drain race: one last assign pass
                        # async discovery still streaming parts in;
                        # back off so a slow listing doesn't turn N
                        # drained workers into a coordinator hot loop
                        time.sleep(idle_sleep)
                        idle_sleep = min(1.0, idle_sleep * 2)
                        continue
                    if linger_wait():
                        continue
                    return
                idle_sleep = 0.05
                if part.stolen_from is not None:
                    self.lease_stats.steals.inc()
                    LEDGER.add(lease_steals=1)
                    trace.instant("lease_steal", part=part.key(),
                                  stolen_from=part.stolen_from,
                                  epoch=part.assignment_epoch)
                    logger.warning(
                        "part %s reclaimed from worker %d (lease "
                        "expired; epoch now %d)", part.key(),
                        part.stolen_from, part.assignment_epoch)
                try:
                    self._upload_part_with_retry(storage, part, schemas)
                except BaseException as e:
                    with err_lock:
                        errors.append(e)
                    return

        hb_stop = threading.Event()

        def heartbeat():
            with trace.adopted(op_ctx), LEDGER.adopted(op_lkey):
                self._heartbeat_loop(hb_stop)

        hb = threading.Thread(target=heartbeat,
                              name=f"heartbeat-{self.worker_index}",
                              daemon=True)
        hb.start()
        try:
            threads = [
                threading.Thread(target=worker, name=f"upload-{i}",
                                 daemon=True)
                for i in range(self.process_count)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            hb_stop.set()
            hb.join(timeout=5.0)
        if errors:
            if is_preemption(errors[0]):
                # yield cleanly: release any claim a sibling left (its
                # part completed or errored by now) so the resuming
                # claimer never waits out this worker's leases
                self.cp.clear_assigned_parts(self.operation_id,
                                             self.worker_index)
            raise errors[0]

    def _upload_part_with_retry(self, storage: Storage,
                                part: OperationTablePart,
                                schemas: dict) -> None:
        def attempt():
            # always-on per-part latency distribution (stats/hdr.py):
            # the mergeable histogram the fleet obs segments export —
            # per-part granularity, so the cost is one bucket add
            from transferia_tpu.stats import hdr

            t0 = time.perf_counter()
            self._upload_part(storage, part, schemas)
            hdr.observe("part_upload", time.perf_counter() - t0)

        # abstract/errors.is_retriable: fatal AND programming/schema
        # errors anywhere in the cause chain fail the part immediately
        # instead of burning the full backoff schedule on a guaranteed
        # re-failure (the TableUploadError wrapper preserves the chain)
        def on_retry(i, e):
            with LEDGER.context(part=part.key()):
                LEDGER.add(retries=1)
            trace.instant("part_retry", part=part.key(), attempt=i,
                          error=type(e).__name__)
            logger.warning("part %s retry %d/%d: %s", part.key(), i,
                           PART_RETRIES, e)

        retry_with_backoff(
            attempt,
            attempts=PART_RETRIES,
            base_delay=PART_RETRY_BASE_DELAY,
            retriable=is_retriable,
            on_retry=on_retry,
        )

    def _commit_and_publish(self, staged, part: OperationTablePart
                            ) -> bool:
        """Phase 2 of the staged commit: ask the coordinator for the
        fenced publish decision, then publish the staged data.  True =
        published (or deliberately published unfenced on a coordinator
        that lost support mid-flight); False = fenced — the caller
        aborts and drops the result."""
        granted = self.cp.commit_part(self.operation_id, part)
        if granted is False:
            self.commit_stats.commit_fenced.inc()
            LEDGER.add(commit_fences=1)
            trace.instant("commit_fenced", part=part.key(),
                          epoch=part.assignment_epoch)
            return False
        if granted is None:
            # the coordinator cannot fence (capability probe raced a
            # downgrade): publishing unfenced degrades this part to
            # at-least-once — never strand staged rows invisibly
            logger.warning(
                "coordinator cannot fence commit of %s; publishing "
                "unfenced (at-least-once for this part)", part.key())
        else:
            part.commit_epoch = part.assignment_epoch
            self.commit_stats.commit_granted.inc()
        try:
            published = staged.publish_part(part.key(),
                                            part.assignment_epoch)
        except StaleEpochPublishError as e:
            # the sink's own epoch fence caught a grant/steal race: a
            # newer owner already published this part
            self.commit_stats.publish_stale_rejected.inc()
            LEDGER.add(commit_fences=1)
            trace.instant("publish_stale_rejected", part=part.key(),
                          epoch=part.assignment_epoch)
            logger.warning("publish of %s rejected by sink fence: %s",
                           part.key(), e)
            return False
        self.commit_stats.published_parts.inc()
        dropped = getattr(staged, "last_dedup_dropped", 0)
        if dropped:
            self.commit_stats.dedup_rows_dropped.inc(dropped)
        LEDGER.add(commits=1)
        trace.instant("part_published", part=part.key(),
                      epoch=part.assignment_epoch, rows=published,
                      dedup_dropped=dropped)
        return True

    def _upload_part(self, storage: Storage, part: OperationTablePart,
                     schemas: dict) -> None:
        """One part: fresh sink pipeline, init/rows/done, progress flush
        (load_snapshot.go:1013-1040)."""
        tid = part.table_id
        schema = schemas.get(tid)
        if schema is None:
            schema = storage.table_schema(tid)
            schemas[tid] = schema
        self._push_scan_predicate(storage, tid, schema)
        part_id = part.part_id() if part.parts_count > 1 else ""
        tap = None
        wrap = None
        if self.transfer.fingerprint_validation():
            from transferia_tpu.middlewares.fingerprint_tap import (
                FingerprintTap,
            )

            def wrap(inner):
                nonlocal tap
                tap = FingerprintTap(inner)
                return tap

        sink = make_async_sink(self.transfer, self.metrics,
                               snapshot_stage=True,
                               post_transform_wrap=wrap)
        # staged two-phase commit (abstract/commit.py): when both ends
        # are capable, this part's batches land invisibly in the sink's
        # staging area and publish only after the coordinator grants a
        # fenced commit_part decision — the exactly-once path.  Either
        # end lacking the capability keeps the at-least-once path.
        staged = find_staged_sink(sink) if self._staged_commits else None
        publish_fenced = False
        rows_done = 0
        read_bytes = 0
        batch_seq = 0
        # root span per part: every stage span a batch triggers on this
        # thread (source decode, transform, device dispatch, sink) nests
        # under it in the exported timeline
        part_sp = trace.span("part")
        if part_sp:
            part_sp.add(transfer_id=self.transfer.id, table=str(tid),
                        part=part.key())
        futures: deque = deque()
        try:
            with part_sp, LEDGER.context(part=part.key()):
                if staged is not None:
                    # a retried part restages from scratch: begin
                    # REPLACES anything a previous attempt staged
                    staged.begin_part(part.key(), part.assignment_epoch)
                    self.commit_stats.staged_parts.inc()
                sink.async_push(
                    [init_table_load(tid, schema, part_id)]
                ).result()

                def pusher(batch):
                    nonlocal rows_done, read_bytes, batch_seq
                    # worker-death injection point (chaos worker_crash:
                    # raise:WorkerKilledError kills this worker mid-part,
                    # leaving the lease to expire for reclamation)
                    failpoint("snapshot.part.batch")
                    sp = trace.span("batch")
                    with sp:
                        if hasattr(batch, "n_rows"):
                            batch.part_id = part_id
                            rows_done += batch.n_rows
                            read_bytes += batch.read_bytes or batch.nbytes()
                            LEDGER.add(rows_in=batch.n_rows,
                                       bytes_in=batch.read_bytes
                                       or batch.nbytes())
                            if sp:
                                sp.add(table=str(tid), part=part.key(),
                                       batch_seq=batch_seq,
                                       rows=batch.n_rows,
                                       bytes=batch.nbytes())
                        else:
                            rows_done += len(batch)
                            LEDGER.add(rows_in=len(batch))
                            if sp:
                                sp.add(table=str(tid), part=part.key(),
                                       batch_seq=batch_seq,
                                       rows=len(batch))
                        batch_seq += 1
                        futures.append(sink.async_push(batch))
                        # bounded in-flight window (deque: the window
                        # slides O(1) per batch, not O(n) list shifts)
                        while len(futures) > 32:
                            futures.popleft().result()

                storage.load_table(part.to_description(), pusher)
                resolve_all(futures)
                sink.async_push(
                    [done_table_load(tid, schema, part_id)]
                ).result()
                if staged is not None:
                    # phase 2: the single fenced publish decision, then
                    # the staged data becomes visible (or is aborted)
                    publish_fenced = not self._commit_and_publish(
                        staged, part)
        except BaseException as e:
            if staged is not None:
                # discard this attempt's staging; a retry re-begins
                # (which replaces) — this only matters on final failure
                try:
                    staged.abort_part(part.key())
                except Exception as abort_err:
                    logger.warning("staged abort of %s failed: %s",
                                   part.key(), abort_err)
            raise TableUploadError(
                f"part {part.key()} failed after {rows_done} rows: {e}",
                cause=e,
            ) from e
        finally:
            # drain/cancel in-flight pushes BEFORE close: on a pusher
            # error, close() must not race pushes still running in the
            # sink's executor (a torn close can double-land a batch)
            while futures:
                f = futures.popleft()
                if not f.cancel():
                    try:
                        f.result(timeout=60.0)
                    # deliberate swallow: this is the error path's drain —
                    # the first failure is already propagating as
                    # TableUploadError above; secondary push errors here
                    # would only mask it
                    except Exception:  # trtpu: ignore[EXC001]
                        pass
            sink.close()
        if publish_fenced:
            # staged-commit fence: the part was reclaimed since our
            # claim (or our publish lost to a newer epoch at the sink).
            # The new owner's publish is authoritative; our staged data
            # was aborted and nothing of ours became visible.  Same
            # engine contract as a fenced update_operation_parts: drop
            # the result, do NOT fail the worker.
            try:
                staged.abort_part(part.key())
            except Exception as abort_err:
                logger.warning("staged abort of %s failed: %s",
                               part.key(), abort_err)
            self.commit_stats.aborted_parts.inc()
            self.lease_stats.fence_rejected.inc()
            logger.warning(
                "part %s publish fenced (stale epoch %d): the part was "
                "reclaimed; staged data discarded, nothing published",
                part.key(), part.assignment_epoch)
            return
        part.completed = True
        part.completed_rows = rows_done
        part.read_bytes = read_bytes
        part.worker_index = self.worker_index
        if tap is not None:
            # digests are keyed by OUTPUT table (transforms may rename or
            # fan out); a single output matching the source keeps the
            # compact legacy form, anything else stores a JSON mapping so
            # `checksum --against-operation` compares target tables under
            # their own names instead of the source's
            aggs = tap.aggregates()
            if len(aggs) == 1 and next(iter(aggs)) == tid:
                part.fingerprint = next(iter(aggs.values())).digest()
            elif aggs:
                import json as _json

                part.fingerprint = _json.dumps(
                    {out.fqtn(): a.digest() for out, a in aggs.items()},
                    sort_keys=True)
        with self._progress_lock:
            rejected = self.cp.update_operation_parts(
                self.operation_id, [part])
            if not rejected:
                self.table_stats.completed_parts.inc()
                self.table_stats.completed_rows.inc(rows_done)
                self._local_parts_done += 1
                self._local_rows_done += rows_done
        if rejected:
            # epoch fence: our lease expired mid-part and the part was
            # reclaimed — the new owner's completion is authoritative,
            # our rows are at-least-once duplicates.  Do NOT fail the
            # worker: drop the stale result and claim the next part
            # (which re-leases us).
            self.lease_stats.fence_rejected.inc(len(rejected))
            logger.warning(
                "part %s completion fenced (stale epoch %d): lease "
                "expired and the part was reclaimed; dropping result",
                part.key(), part.assignment_epoch)
            return
        # device counters surface on this pipeline's metrics as parts
        # complete (H2D/D2H bytes, launches, XLA compiles) — the
        # attribution ledger folds alongside so the ledger_* series
        # track the same cadence
        trace.TELEMETRY.fold_into(self.metrics)
        LEDGER.fold_into(self.metrics)
        # part completion is an export trigger (coalesced inside the
        # exporter): the committed part's spend is durable immediately
        self._obs.export("part")
        logger.info("part %s done: %d rows, %d bytes",
                    part.key(), rows_done, read_bytes)
