"""Transfer maintenance operations: reupload, add_tables, remove_tables.

Reference parity: pkg/abstract/task_type.go (the operation enum),
pkg/worker/tasks/reupload.go (stop job -> cleanup per policy -> full
snapshot -> restart), add_tables.go (load only the new tables, then widen
the endpoint's include list and persist it through the coordinator),
remove_tables.go (narrow the include list; target data is left in place).

The reference gates add/remove on pg sources (add_tables.go:19
"obsolete and supported only for pg sources"); here any storage-capable
source qualifies — the constraint was a legacy-endpoint artifact, not a
semantic one, and the include-list lives on the Transfer (DataObjects)
rather than inside provider params.
"""

from __future__ import annotations

import logging
from typing import Optional

from transferia_tpu.abstract.schema import TableID
from transferia_tpu.coordinator.interface import Coordinator, TransferStatus
from transferia_tpu.factories import new_storage
from transferia_tpu.models import CleanupPolicy
from transferia_tpu.models.endpoint import capability
from transferia_tpu.providers.registry import get_provider
from transferia_tpu.stats.registry import Metrics
from transferia_tpu.tasks.snapshot import SnapshotLoader

logger = logging.getLogger(__name__)

INCLUDE_STATE_KEY = "include_object_ids"


def reupload(transfer, coordinator: Coordinator,
             metrics: Optional[Metrics] = None,
             operation_id: Optional[str] = None) -> None:
    """Full re-snapshot of an activated transfer (reupload.go:20).

    Forbidden for append-only sources (reupload.go:13): wiping the target
    of a queue-backed transfer would lose history the source no longer
    holds.
    """
    if capability(transfer.src, "is_append_only", False):
        raise ValueError("reupload from an append-only source is not "
                         "allowed (reupload.go:13)")
    metrics = metrics or Metrics()
    coordinator.set_status(transfer.id, TransferStatus.ACTIVATING)
    try:
        loader = SnapshotLoader(transfer, coordinator, metrics=metrics,
                                operation_id=operation_id)
        storage = new_storage(transfer, metrics)
        try:
            tables = loader.filtered_table_list(storage)
        finally:
            storage.close()
        if transfer.dst.cleanup_policy != CleanupPolicy.DISABLED:
            dst_provider = get_provider(transfer.dst_provider(), transfer,
                                        metrics)
            logger.info("reupload cleanup (%s): %d tables",
                        transfer.dst.cleanup_policy.value, len(tables))
            dst_provider.cleanup(tables)
        loader.upload_tables(tables)
        coordinator.set_status(transfer.id, TransferStatus.ACTIVATED)
    except BaseException as e:
        coordinator.set_status(transfer.id, TransferStatus.FAILED)
        coordinator.open_status_message(transfer.id, "reupload", str(e))
        raise


def add_tables(transfer, coordinator: Coordinator, tables: list[str],
               metrics: Optional[Metrics] = None,
               operation_id: Optional[str] = None) -> None:
    """Snapshot-load new tables into a live transfer, then widen its
    include list (add_tables.go:26).

    Only the added tables are loaded — existing target data is untouched
    (no cleanup pass, matching the reference flow which transfers the new
    tables' schema + data before updating the endpoint).
    """
    if not tables:
        raise ValueError("add_tables: explicit table list required")
    current = set(transfer.data_objects.include_object_ids)
    if not current:
        raise ValueError(
            "add_tables requires a transfer with an explicit include "
            "list (a transfer without one already moves every table)")
    new = [t for t in tables if t not in current]
    if not new:
        logger.info("add_tables: all requested tables already included")
        return
    metrics = metrics or Metrics()
    from transferia_tpu.abstract.table import TableDescription

    loader = SnapshotLoader(transfer, coordinator, metrics=metrics,
                            operation_id=operation_id)
    loader.upload_tables([
        TableDescription(id=TableID.parse(t)) for t in new
    ])
    transfer.data_objects.include_object_ids.extend(new)
    _persist_include_list(transfer, coordinator)
    logger.info("add_tables: loaded and registered %d tables", len(new))


def remove_tables(transfer, coordinator: Coordinator,
                  tables: list[str],
                  metrics: Optional[Metrics] = None) -> None:
    """Narrow the include list (remove_tables.go:20).  Target data for the
    removed tables stays in place, as in the reference."""
    if not tables:
        raise ValueError("remove_tables: explicit table list required")
    current = transfer.data_objects.include_object_ids
    if not current:
        raise ValueError(
            "remove_tables requires a transfer with an explicit include "
            "list")
    drop = set(tables)
    kept = [t for t in current if t not in drop]
    missing = drop - set(current)
    if missing:
        raise ValueError(f"remove_tables: not in the include list: "
                         f"{sorted(missing)}")
    if not kept:
        raise ValueError("remove_tables: refusing to empty the include "
                         "list (deactivate the transfer instead)")
    transfer.data_objects.include_object_ids = kept
    _persist_include_list(transfer, coordinator)
    logger.info("remove_tables: %d tables remain", len(kept))


def _persist_include_list(transfer, coordinator: Coordinator) -> None:
    """Store the effective include list in transfer state so restarted
    workers see the updated table set (add_tables.go persists the endpoint
    through cp.GetEndpoint/UpdateEndpoint; our include list is transfer-
    level DataObjects, so it rides the transfer-state KV)."""
    state = coordinator.get_transfer_state(transfer.id)
    state[INCLUDE_STATE_KEY] = list(transfer.data_objects.include_object_ids)
    coordinator.set_transfer_state(transfer.id, state)


def apply_persisted_include_list(transfer, coordinator: Coordinator) -> None:
    """Merge a previously persisted include list back onto the transfer
    (called by the replicate/activate entry points on restart)."""
    state = coordinator.get_transfer_state(transfer.id)
    stored = state.get(INCLUDE_STATE_KEY)
    if stored:
        transfer.data_objects.include_object_ids = list(stored)
