"""Checksum: source/target data validation (pkg/worker/tasks/checksum.go).

Compares row counts and sampled rows between the transfer's source storage
and a storage view of the destination, with type-aware comparators
(checksum.go:35-50: floats rounded to 12 significant digits, bytes/str
unified, NULL == NULL).
"""

from __future__ import annotations

import logging
import math
from dataclasses import dataclass, field
from typing import Any, Optional

from transferia_tpu.abstract.interfaces import (
    SampleableStorage,
    Storage,
)
from transferia_tpu.abstract.schema import TableID
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.stats.registry import Metrics

logger = logging.getLogger(__name__)


@dataclass
class TableChecksum:
    table: TableID
    source_rows: int = 0
    target_rows: int = 0
    compared_rows: int = 0
    mismatches: list[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.source_rows == self.target_rows and not self.mismatches


@dataclass
class ChecksumReport:
    tables: list[TableChecksum] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return all(t.ok for t in self.tables)

    def summary(self) -> str:
        lines = []
        for t in self.tables:
            status = "OK" if t.ok else "MISMATCH"
            lines.append(
                f"{t.table}: {status} (src={t.source_rows} "
                f"dst={t.target_rows} compared={t.compared_rows} "
                f"diffs={len(t.mismatches)})"
            )
        return "\n".join(lines)


def values_equal(a: Any, b: Any) -> bool:
    """Type-aware comparator (checksum.go:35-50)."""
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, bytes) and isinstance(b, str):
        return a.decode("utf-8", errors="replace") == b
    if isinstance(a, str) and isinstance(b, bytes):
        return a == b.decode("utf-8", errors="replace")
    if isinstance(a, bool) or isinstance(b, bool):
        return bool(a) == bool(b)
    if isinstance(a, float) or isinstance(b, float):
        try:
            fa, fb = float(a), float(b)
        except (TypeError, ValueError):
            return a == b
        if math.isnan(fa) and math.isnan(fb):
            return True
        if fa == fb:
            return True
        # round to 12 significant digits (reference float policy)
        return f"{fa:.12g}" == f"{fb:.12g}"
    return a == b


def _collect_rows(storage: Storage, td: TableDescription, limit: int
                  ) -> list[dict]:
    rows: list[dict] = []

    def pusher(batch):
        if len(rows) >= limit:
            return
        items = batch.to_rows() if hasattr(batch, "to_rows") else batch
        for it in items:
            if getattr(it, "is_row_event", lambda: False)():
                rows.append(it.as_dict())
                if len(rows) >= limit:
                    return

    if isinstance(storage, SampleableStorage):
        storage.load_top_bottom_sample(td, pusher)
    else:
        storage.load_table(td, pusher)
    return rows[:limit]


def checksum(source_storage: Storage, target_storage: Storage,
             tables: Optional[list[TableID]] = None,
             sample_rows: int = 1000,
             metrics: Optional[Metrics] = None) -> ChecksumReport:
    report = ChecksumReport()
    src_tables = source_storage.table_list(
        [TableID(t.namespace, t.name) for t in tables] if tables else None
    )
    for tid in src_tables:
        tc = TableChecksum(table=tid)
        report.tables.append(tc)
        tc.source_rows = source_storage.exact_table_rows_count(tid)
        try:
            tc.target_rows = target_storage.exact_table_rows_count(tid)
        except Exception as e:
            tc.mismatches.append(f"target count failed: {e}")
            continue
        td = TableDescription(id=tid)
        src_rows = _collect_rows(source_storage, td, sample_rows)
        dst_rows = _collect_rows(target_storage, td, sample_rows)
        # key rows by primary key when available, else by position
        schema = source_storage.table_schema(tid)
        keys = [c.name for c in schema.key_columns()] if schema else []
        if keys:
            dst_by_key = {
                tuple(r.get(k) for k in keys): r for r in dst_rows
            }
            for r in src_rows:
                key = tuple(r.get(k) for k in keys)
                other = dst_by_key.get(key)
                if other is None:
                    tc.mismatches.append(f"row {key} missing in target")
                    continue
                tc.compared_rows += 1
                for col, val in r.items():
                    if col in other and not values_equal(val, other[col]):
                        tc.mismatches.append(
                            f"row {key} col {col}: "
                            f"{val!r} != {other[col]!r}"
                        )
        else:
            for i, (a, b) in enumerate(zip(src_rows, dst_rows)):
                tc.compared_rows += 1
                for col, val in a.items():
                    if col in b and not values_equal(val, b[col]):
                        tc.mismatches.append(
                            f"row #{i} col {col}: {val!r} != {b[col]!r}"
                        )
        if len(tc.mismatches) > 20:
            tc.mismatches = tc.mismatches[:20] + ["...truncated"]
    return report
