"""Checksum: source/target data validation (pkg/worker/tasks/checksum.go).

Reference-depth compare between the transfer's source storage and a storage
view of the destination:

- schema + primary-key comparison up front (checksum.go compareSchema /
  comparePrimaryKeys);
- size-gated strategy (checksum.go:36 defaultTableSizeThreshold): small
  tables are fully compared, big tables via top/bottom + random key samples
  (abstract/storage.go:322-337 Sampleable/ChecksumableStorage);
- the full compare streams with bounded memory: source rows are pulled in
  chunks and matched against the target via LoadSampleBySet, so no table
  is ever held in RAM (improves on the reference's O(table) keyset maps);
- type-aware comparators (checksum.go:35-50, tryCompare at :861): floats
  rounded to 12 significant digits, temporal normalization, NULL == NULL,
  bytes/str unification, arrays element-wise, pg interval/geometry text
  normalization, json string-compare;
- error map with per-kind counts and capped samples (checksum.go errorMap),
  per-table compare retries (compareRetryThreshold = 3).
"""

from __future__ import annotations

import datetime as _dt
import logging
import math
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

from transferia_tpu.abstract.interfaces import (
    SampleableStorage,
    Storage,
)
from transferia_tpu.abstract.schema import ColSchema, TableID
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.stats.registry import Metrics

logger = logging.getLogger(__name__)

ROUNDING_DIGITS = 12                       # checksum.go:44 roundingConst
DEFAULT_TABLE_SIZE_THRESHOLD = 20 << 20    # checksum.go:36 (20 MiB)
COMPARE_RETRIES = 3                        # checksum.go:37
MAX_ERROR_SAMPLES = 3                      # checksum.go:38
KEYSET_CHUNK = 512                         # streaming-compare chunk (keys)

GENERIC_ERROR = "generic"
SCHEMA_MISMATCH_ERROR = "table schema mismatch"
MISSED_KEY_ERROR = "missed key"

# priority comparator signature (checksum.go:49 ChecksumComparator):
# (lval, lschema, rval, rschema, into_array) -> (comparable, equal)
Comparator = Callable[[Any, ColSchema, Any, ColSchema, bool],
                      tuple[bool, bool]]


class ComparisonError(Exception):
    """A value pair could not be compared (parser failure etc.)."""


@dataclass
class ChecksumParameters:
    """Knobs for the checksum task (checksum.go:120 ChecksumParameters)."""

    table_size_threshold: int = DEFAULT_TABLE_SIZE_THRESHOLD
    tables: list[TableID] = field(default_factory=list)
    priority_comparators: list[Comparator] = field(default_factory=list)
    keyset_chunk: int = KEYSET_CHUNK
    # cap on rows compared per table in the full strategy (0 = whole
    # table); the quick `check` command sets this from sample_rows
    max_rows: int = 0
    # "compare" (the reference's row-by-row strategies) or "fingerprint":
    # stream both tables through the order-independent device-reducible
    # digest (ops/rowhash.py) and compare aggregates — O(1) memory per
    # table, exact-representation semantics; on mismatch the row-level
    # strategy runs for that table as the diagnostic pass
    method: str = "compare"
    # fingerprint backend: auto | host | device (ops/rowhash.py)
    fingerprint_backend: str = "auto"


# ---------------------------------------------------------------------------
# error map (checksum.go errorMap)


@dataclass
class _ErrorEntry:
    count: int = 0
    samples: list[str] = field(default_factory=list)


class ErrorMap:
    def __init__(self):
        self._by_table: dict[str, dict[str, _ErrorEntry]] = {}

    def add(self, fqtn: str, kind: str, description: str) -> None:
        entry = self._by_table.setdefault(fqtn, {}).setdefault(
            kind, _ErrorEntry())
        entry.count += 1
        if len(entry.samples) < MAX_ERROR_SAMPLES:
            entry.samples.append(description)
        logger.debug("table %s, %s error: %s", fqtn, kind, description)

    def clear_table(self, fqtn: str) -> None:
        self._by_table[fqtn] = {}

    def table_errors(self, fqtn: str) -> list[str]:
        out = []
        for kind, entry in self._by_table.get(fqtn, {}).items():
            for i, s in enumerate(entry.samples):
                out.append(f"{kind} ({i + 1} of {entry.count}): {s}")
        return out

    def total(self) -> int:
        return sum(e.count for kinds in self._by_table.values()
                   for e in kinds.values())


# ---------------------------------------------------------------------------
# report


@dataclass
class TableChecksum:
    table: TableID
    source_rows: int = 0
    target_rows: int = 0
    compared_rows: int = 0
    # "full" | "sample" | "fingerprint" | "fingerprint+{full,sample}"
    strategy: str = "full"
    mismatches: list[str] = field(default_factory=list)
    # non-failing observations (e.g. exact-representation fingerprint
    # drift that the tolerant row comparators then cleared)
    notes: list[str] = field(default_factory=list)
    source_fingerprint: str = ""
    target_fingerprint: str = ""

    @property
    def ok(self) -> bool:
        return self.source_rows == self.target_rows and not self.mismatches

    def fqtn(self) -> str:
        return self.table.fqtn()


@dataclass
class ChecksumReport:
    tables: list[TableChecksum] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return all(t.ok for t in self.tables)

    def summary(self) -> str:
        lines = []
        for t in self.tables:
            status = "OK" if t.ok else "MISMATCH"
            lines.append(
                f"{t.table}: {status} [{t.strategy}] (src={t.source_rows} "
                f"dst={t.target_rows} compared={t.compared_rows} "
                f"diffs={len(t.mismatches)})"
            )
            for m in t.mismatches[:MAX_ERROR_SAMPLES * 4]:
                lines.append(f"  - {m}")
            for m in t.notes[:MAX_ERROR_SAMPLES]:
                lines.append(f"  ~ note: {m}")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# type-aware comparators (checksum.go:861 tryCompare and friends)


def _is_number(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _extract_double(v: Any) -> float:
    if isinstance(v, bool):
        raise ComparisonError(f"cannot treat bool {v!r} as double")
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, str):
        try:
            return float(v)
        except ValueError as e:
            raise ComparisonError(f"cannot parse {v!r} as double") from e
    raise ComparisonError(f"cannot convert {type(v).__name__} to double")


def _round12(x: float) -> str:
    """Fixed 12-decimal rounding (checksum.go rounded())."""
    return f"{x:.{ROUNDING_DIGITS}f}"


_TEMPORAL_FORMATS = (
    "%Y-%m-%d %H:%M:%S.%f%z", "%Y-%m-%d %H:%M:%S%z",
    "%Y-%m-%d %H:%M:%S.%f", "%Y-%m-%d %H:%M:%S",
    "%Y-%m-%dT%H:%M:%S.%f%z", "%Y-%m-%dT%H:%M:%S%z",
    "%Y-%m-%dT%H:%M:%S.%f", "%Y-%m-%dT%H:%M:%S",
    "%Y-%m-%d",
)


def _parse_temporal(v: Any) -> Optional[_dt.datetime]:
    if isinstance(v, _dt.datetime):
        return v
    if isinstance(v, _dt.date):
        return _dt.datetime(v.year, v.month, v.day)
    if not isinstance(v, str) or not v:
        return None
    s = v.strip()
    # normalize short tz offsets ("+00" -> "+0000") for strptime
    if len(s) > 3 and s[-3] in "+-" and s[-2:].isdigit():
        s = s + "00"
    try:
        return _dt.datetime.fromisoformat(v.strip())
    except ValueError:
        pass
    for fmt in _TEMPORAL_FORMATS:
        try:
            return _dt.datetime.strptime(s, fmt)
        except ValueError:
            continue
    return None


def _as_utc(t: _dt.datetime) -> _dt.datetime:
    if t.tzinfo is None:
        return t.replace(tzinfo=_dt.timezone.utc)
    return t.astimezone(_dt.timezone.utc)


def _original(schema: Optional[ColSchema]) -> str:
    return (schema.original_type or "") if schema is not None else ""


def _pg_type(schema: Optional[ColSchema]) -> str:
    ot = _original(schema)
    if not ot.startswith("pg:"):
        return ""
    # "pg:numeric(10,2)[]" -> "numeric"
    t = ot[3:].split("(")[0].rstrip("[]").strip().lower()
    return t


def _looks_temporal(schema: Optional[ColSchema]) -> bool:
    ot = _original(schema).lower()
    return any(k in ot for k in ("timestamp", "datetime", "date", "time"))


def compare_pg_interval(a: str, b: str) -> bool:
    """Textual interval compare ignoring trailing zero fields
    (checksum.go comparePGInterval)."""
    a = a.replace("days", "day")
    b = b.replace("days", "day")
    if len(a) > len(b):
        a, b = b, a
    if b[:len(a)] != a:
        return False
    return all(ch in "0.: " for ch in b[len(a):])


def _parse_points(s: str) -> list[float]:
    """All floats in a pg geometry literal, rounded to 12 decimals."""
    out: list[float] = []
    num = ""
    for ch in s:
        if ch.isdigit() or ch in ".-+eE":
            num += ch
        else:
            if num:
                try:
                    out.append(float(_round12(float(num))))
                except ValueError as e:
                    raise ComparisonError(
                        f"bad geometry literal {s!r}") from e
                num = ""
    if num:
        try:
            out.append(float(_round12(float(num))))
        except ValueError as e:
            raise ComparisonError(f"bad geometry literal {s!r}") from e
    return out


def compare_pg_geometry(a: str, b: str) -> bool:
    """Box/circle/polygon/point compare by rounded coordinate lists
    (checksum.go parseBox/parseCircle/parsePolygon)."""
    return _parse_points(a) == _parse_points(b)


def compare_pg_lseg(a: str, b: str) -> bool:
    """Segment compare after bracket normalization
    (checksum.go compareSegments)."""
    def norm(s: str) -> str:
        for src, dst in (("[(", "("), (")]", ")"), ("((", "("), ("))", ")")):
            s = s.replace(src, dst)
        return s
    return norm(a) == norm(b)


def try_compare(lval: Any, lschema: Optional[ColSchema],
                rval: Any, rschema: Optional[ColSchema],
                priority_comparators: Sequence[Comparator] = (),
                into_array: bool = False) -> bool:
    """Type-aware value equality (checksum.go:861 tryCompare).

    Raises ComparisonError when the pair cannot be compared at all.
    """
    # fast path: identical textual representation
    if str(lval) == str(rval):
        return True

    for pc in priority_comparators:
        comparable, equal = pc(lval, lschema, rval, rschema, into_array)
        if comparable:
            return equal

    # NULLs
    if lval is None or rval is None:
        return lval is None and rval is None

    # bools before numbers (bool is an int subtype in Python)
    if isinstance(lval, bool) or isinstance(rval, bool):
        def as_bool(v):
            if isinstance(v, bool):
                return v
            if isinstance(v, (int, float)):
                return v != 0
            if isinstance(v, str):
                return v.lower() in ("t", "true", "1")
            raise ComparisonError(f"cannot treat {v!r} as bool")
        return as_bool(lval) == as_bool(rval)

    # arrays: element-wise with the element schema
    if isinstance(lval, (list, tuple)) and isinstance(rval, (list, tuple)):
        if len(lval) != len(rval):
            return False
        return all(
            try_compare(a, lschema, b, rschema, priority_comparators, True)
            for a, b in zip(lval, rval)
        )

    # temporal normalization
    if (_looks_temporal(lschema) or _looks_temporal(rschema)
            or isinstance(lval, (_dt.datetime, _dt.date))
            or isinstance(rval, (_dt.datetime, _dt.date))):
        lt, rt = _parse_temporal(lval), _parse_temporal(rval)
        if lt is not None and rt is not None:
            return _as_utc(lt) == _as_utc(rt)

    # pg text-normalized types
    lpg, rpg = _pg_type(lschema), _pg_type(rschema)
    if "interval" in (lpg, rpg) and isinstance(lval, str) \
            and isinstance(rval, str):
        return compare_pg_interval(lval, rval)
    if "lseg" in (lpg, rpg) and isinstance(lval, str) \
            and isinstance(rval, str):
        return compare_pg_lseg(lval, rval)
    if any(t in ("box", "circle", "polygon", "point", "path")
           for t in (lpg, rpg)) \
            and isinstance(lval, str) and isinstance(rval, str):
        return compare_pg_geometry(lval, rval)

    # bytes vs str
    if isinstance(lval, (bytes, bytearray)) or \
            isinstance(rval, (bytes, bytearray)):
        def as_bytes(v):
            if isinstance(v, (bytes, bytearray)):
                return bytes(v)
            if isinstance(v, str):
                if v.startswith("\\x"):
                    try:
                        return bytes.fromhex(v[2:])
                    except ValueError:
                        pass
                return v.encode()
            raise ComparisonError(f"cannot treat {v!r} as bytes")
        return as_bytes(lval) == as_bytes(rval)

    # json columns: string compare of the canonical repr
    lot, rot = _original(lschema).lower(), _original(rschema).lower()
    if any(t.endswith((":json", ":jsonb")) for t in (lot, rot)):
        return str(lval) == str(rval)

    # floats: exact first, then 12-significant-digit rounding
    if isinstance(lval, float) or isinstance(rval, float) or (
            _is_number(lval) and _is_number(rval)):
        try:
            lf, rf = _extract_double(lval), _extract_double(rval)
        except ComparisonError:
            return lval == rval
        if math.isnan(lf) and math.isnan(rf):
            return True
        if lf == rf:
            return True
        return f"{lf:.{ROUNDING_DIGITS}g}" == f"{rf:.{ROUNDING_DIGITS}g}"

    # numeric strings ("1.50" vs 1.5) when either side declares a number
    if isinstance(lval, str) or isinstance(rval, str):
        try:
            return _extract_double(lval) == _extract_double(rval)
        except ComparisonError:
            pass

    return lval == rval


def values_equal(a: Any, b: Any,
                 a_schema: Optional[ColSchema] = None,
                 b_schema: Optional[ColSchema] = None) -> bool:
    """Back-compat wrapper over try_compare."""
    try:
        return try_compare(a, a_schema, b, b_schema)
    except ComparisonError:
        return False


# ---------------------------------------------------------------------------
# row collection helpers


def _iter_rows(batch) -> list:
    items = batch.to_rows() if hasattr(batch, "to_rows") else batch
    return [it for it in items
            if getattr(it, "is_row_event", lambda: False)()]


def _row_key(row: dict, keys: Sequence[str]) -> tuple:
    return tuple(row.get(k) for k in keys)


def _collect_keyed(storage: Storage, loader: str, td: TableDescription,
                   keys: Sequence[str], *args) -> dict[tuple, dict]:
    """Run a sample loader and key the resulting rows by primary key."""
    out: dict[tuple, dict] = {}

    def pusher(batch):
        for it in _iter_rows(batch):
            d = it.as_dict()
            out[_row_key(d, keys)] = d

    getattr(storage, loader)(td, *args, pusher)
    return out


def _schema_maps(storage: Storage, tid: TableID):
    schema = storage.table_schema(tid)
    cols = {c.name: c for c in schema} if schema else {}
    keys = [c.name for c in schema.key_columns()] if schema else []
    return schema, cols, keys


def _table_size(storage: Storage, tid: TableID) -> int:
    fn = getattr(storage, "table_size_in_bytes", None)
    if fn is None:
        return 0
    try:
        return int(fn(tid) or 0)
    except Exception as e:
        logger.debug("table_size_in_bytes failed for %s: %s", tid, e)
        return 0


# ---------------------------------------------------------------------------
# per-table comparison strategies


def _compare_rows(tc: TableChecksum,
                  lrow: dict, rrow: dict, key: tuple,
                  lcols: dict[str, ColSchema], rcols: dict[str, ColSchema],
                  comparators: Sequence[Comparator]) -> None:
    tc.compared_rows += 1
    for col, lv in lrow.items():
        if col not in rrow:
            continue
        try:
            equal = try_compare(lv, lcols.get(col), rrow[col],
                                rcols.get(col), comparators)
        except ComparisonError as e:
            tc.mismatches.append(f"row {key} col {col}: {e}")
            continue
        if not equal:
            tc.mismatches.append(
                f"row {key} col {col}: {lv!r} != {rrow[col]!r}")


def _stream_full_compare(tc: TableChecksum, errors: ErrorMap,
                         src: Storage, dst: Storage, td: TableDescription,
                         keys: Sequence[str],
                         lcols: dict, rcols: dict,
                         params: ChecksumParameters) -> None:
    """Bounded-memory full compare: pull source rows in chunks, match each
    chunk against the target via LoadSampleBySet.

    Falls back to a one-shot target load when the target storage has no
    sampling capability (memory/test storages)."""
    comparators = params.priority_comparators
    dst_sampleable = isinstance(dst, SampleableStorage)

    dst_all: dict[tuple, dict] = {}
    if not dst_sampleable:
        def dst_pusher(batch):
            for it in _iter_rows(batch):
                d = it.as_dict()
                dst_all[_row_key(d, keys)] = d
        dst.load_table(td, dst_pusher)

    pending: list[dict] = []
    seen = [0]

    def flush():
        if not pending:
            return
        if dst_sampleable:
            key_set = [{k: r.get(k) for k in keys} for r in pending]
            found = _collect_keyed(dst, "load_sample_by_set", td, keys,
                                   key_set)
        else:
            found = dst_all
        for lrow in pending:
            key = _row_key(lrow, keys)
            rrow = found.get(key)
            if rrow is None:
                tc.mismatches.append(f"row {key} missing in target")
                continue
            _compare_rows(tc, lrow, rrow, key, lcols, rcols,
                          comparators)
        pending.clear()

    def src_pusher(batch):
        for it in _iter_rows(batch):
            if params.max_rows and seen[0] >= params.max_rows:
                return
            pending.append(it.as_dict())
            seen[0] += 1
            if len(pending) >= params.keyset_chunk:
                flush()

    src.load_table(td, src_pusher)
    flush()


def _sampled_compare(tc: TableChecksum, errors: ErrorMap,
                     src: SampleableStorage, dst: Storage,
                     td: TableDescription, keys: Sequence[str],
                     lcols: dict, rcols: dict,
                     params: ChecksumParameters) -> None:
    """Big-table compare (checksum.go:238-337): top/bottom sample with
    retries, then a random keyset verified via LoadSampleBySet."""
    comparators = params.priority_comparators
    dst_sampleable = isinstance(dst, SampleableStorage)

    def match_keyed(left: dict[tuple, dict], right: dict[tuple, dict],
                    count_missing_right: bool = False) -> int:
        before = len(tc.mismatches)
        for key, lrow in left.items():
            rrow = right.get(key)
            if rrow is None:
                tc.mismatches.append(f"row {key} missing in target")
                continue
            _compare_rows(tc, lrow, rrow, key, lcols, rcols,
                          comparators)
        if count_missing_right:
            for key in right:
                if key not in left:
                    tc.mismatches.append(f"row {key} missing in source")
        return len(tc.mismatches) - before

    # top/bottom sample, retried (compareRetryThreshold)
    matched = False
    for attempt in range(COMPARE_RETRIES):
        saved = list(tc.mismatches)
        saved_compared = tc.compared_rows
        left = _collect_keyed(src, "load_top_bottom_sample", td, keys)
        if dst_sampleable:
            right = _collect_keyed(dst, "load_top_bottom_sample", td, keys)
        else:
            right = {}
            def dst_pusher(batch):
                for it in _iter_rows(batch):
                    d = it.as_dict()
                    right[_row_key(d, keys)] = d
            dst.load_table(td, dst_pusher)
        # when both sides sample identical top/bottom windows, an extra
        # key in the target is as much a defect as a missing one; the
        # full-load fallback right side legitimately holds extra keys
        if match_keyed(left, right,
                       count_missing_right=dst_sampleable) == 0:
            matched = True
            errors.clear_table(tc.fqtn())
            break
        logger.warning("top-bottom sample for %s mismatched, retrying "
                       "(%d/%d)", tc.fqtn(), attempt + 1, COMPARE_RETRIES)
        tc.mismatches = saved
        tc.compared_rows = saved_compared
        time.sleep(attempt * 0.2)
    if not matched:
        # re-run once more to leave the mismatch details in the report
        left = _collect_keyed(src, "load_top_bottom_sample", td, keys)
        right = (_collect_keyed(dst, "load_top_bottom_sample", td, keys)
                 if dst_sampleable else right)
        match_keyed(left, right, count_missing_right=dst_sampleable)
        return

    # random keyset probe (checksum.go:306-337)
    left = _collect_keyed(src, "load_random_sample", td, keys)
    if not left:
        return
    key_set = [dict(zip(keys, k)) for k in left]
    if dst_sampleable:
        right = _collect_keyed(dst, "load_sample_by_set", td, keys, key_set)
    else:
        right = {}
        def dst_pusher(batch):
            for it in _iter_rows(batch):
                d = it.as_dict()
                k = _row_key(d, keys)
                if k in left:
                    right[k] = d
        dst.load_table(td, dst_pusher)
    match_keyed(left, right)


# ---------------------------------------------------------------------------
# schema comparison (checksum.go compareSchema / comparePrimaryKeys)


def _compare_schemas(tc: TableChecksum, errors: ErrorMap,
                     lcols: dict[str, ColSchema],
                     rcols: dict[str, ColSchema],
                     lkeys: Sequence[str], rkeys: Sequence[str],
                     equal_data_types: Callable[[str, str], bool]) -> bool:
    ok = True
    for name in set(lcols) | set(rcols):
        if name not in lcols:
            errors.add(tc.fqtn(), SCHEMA_MISMATCH_ERROR,
                       f"column '{name}' not found in source table")
            ok = False
        elif name not in rcols:
            errors.add(tc.fqtn(), SCHEMA_MISMATCH_ERROR,
                       f"column '{name}' not found in target table")
            ok = False
        elif not equal_data_types(lcols[name].data_type.value,
                                  rcols[name].data_type.value):
            errors.add(tc.fqtn(), SCHEMA_MISMATCH_ERROR,
                       f"column types differ for column '{name}': "
                       f"(source) {lcols[name].data_type} != "
                       f"{rcols[name].data_type} (target)")
            ok = False
    if list(lkeys) != list(rkeys):
        errors.add(tc.fqtn(), SCHEMA_MISMATCH_ERROR,
                   f"primary keys differ: (source) {list(lkeys)} != "
                   f"{list(rkeys)} (target)")
        ok = False
    if not ok:
        tc.mismatches.extend(errors.table_errors(tc.fqtn()))
    return ok


_TYPE_FAMILIES = (
    {"int8", "int16", "int32", "int64",
     "uint8", "uint16", "uint32", "uint64"},
    {"float", "double"},
    {"date", "datetime", "timestamp"},
    # heterogeneous sinks without native decimal/json store them as text
    {"string", "utf8", "any", "decimal"},
    {"interval", "int64"},
)


def heterogeneous_data_types(a: str, b: str) -> bool:
    """Data-type equality for cross-provider checksums: exact match or the
    same family after target-rule widening (e.g. pg text -> CH String,
    pg numeric -> CH String)."""
    a, b = a.lower(), b.lower()
    if a == b:
        return True
    return any(a in fam and b in fam for fam in _TYPE_FAMILIES)


# ---------------------------------------------------------------------------
# entry points


def compare_checksum(src: Storage, dst: Storage,
                     tables: Optional[list[TableID]] = None,
                     params: Optional[ChecksumParameters] = None,
                     equal_data_types: Callable[[str, str], bool] =
                     lambda a, b: a == b,
                     metrics: Optional[Metrics] = None) -> ChecksumReport:
    """Compare src and dst storages table by table (CompareChecksum)."""
    params = params or ChecksumParameters()
    errors = ErrorMap()
    report = ChecksumReport()
    want = tables or params.tables or None
    src_tables = src.table_list(
        [TableID(t.namespace, t.name) for t in want] if want else None)
    for tid in src_tables:
        tc = TableChecksum(table=tid)
        report.tables.append(tc)
        try:
            tc.source_rows = src.exact_table_rows_count(tid)
            tc.target_rows = dst.exact_table_rows_count(tid)
        except Exception as e:
            errors.add(tc.fqtn(), GENERIC_ERROR, f"row count failed: {e}")
            tc.mismatches.append(f"row count failed: {e}")
            continue
        if tc.source_rows != tc.target_rows:
            tc.mismatches.append(
                f"row counts differ: src={tc.source_rows} "
                f"dst={tc.target_rows}")

        _, lcols, lkeys = _schema_maps(src, tid)
        _, rcols, rkeys = _schema_maps(dst, tid)
        if not _compare_schemas(tc, errors, lcols, rcols, lkeys, rkeys,
                                equal_data_types):
            continue

        td = TableDescription(id=tid)
        if params.method == "fingerprint" and \
                tc.source_rows == tc.target_rows:
            # differing row counts are already a verdict — skip the
            # full-scan digest and go straight to row-level diagnosis
            matched = _fingerprint_compare(tc, errors, src, dst, td,
                                           params)
            if matched:
                continue
            # aggregate mismatch: fall through to the row-level strategy
            # below so the report pinpoints rows, not just the table
        size = _table_size(src, tid)
        sampled = (size > params.table_size_threshold
                   and isinstance(src, SampleableStorage)
                   and bool(lkeys))
        tc.strategy = ("fingerprint+sample" if params.method ==
                       "fingerprint" else "sample") if sampled else \
            ("fingerprint+full" if params.method == "fingerprint"
             else "full")
        pre_row_mismatches = len(tc.mismatches)
        try:
            if sampled:
                _sampled_compare(tc, errors, src, dst, td, lkeys,
                                 lcols, rcols, params)
            elif lkeys:
                _stream_full_compare(tc, errors, src, dst, td, lkeys,
                                     lcols, rcols, params)
            else:
                _positional_compare(tc, errors, src, dst, td,
                                    lcols, rcols, params)
        except Exception as e:
            errors.add(tc.fqtn(), GENERIC_ERROR, f"compare failed: {e}")
            tc.mismatches.append(f"compare failed: {e}")
        if (not sampled
                and len(tc.mismatches) == pre_row_mismatches
                and tc.mismatches
                and all(m.startswith("fingerprints differ")
                        for m in tc.mismatches)):
            # the exact-representation digest flagged drift but the
            # (family-level, tolerant) row comparators found zero row
            # differences across a FULL-coverage pass: that is encoding
            # drift, not a data mismatch — report it without failing the
            # table.  Under fingerprint+sample the row compare only saw a
            # sample, so the digest mismatch stands (the difference may
            # live in unsampled rows).
            tc.notes.extend(
                m + " (representation-only: row-level compare found "
                    "no differences)" for m in tc.mismatches)
            tc.mismatches.clear()
        if len(tc.mismatches) > 50:
            tc.mismatches = tc.mismatches[:50] + ["...truncated"]
    return report


def _fingerprint_compare(tc: TableChecksum, errors: ErrorMap,
                         src: Storage, dst: Storage,
                         td: TableDescription,
                         params: ChecksumParameters) -> bool:
    """Order-independent digest compare (ops/rowhash.py).

    Streams both tables through TableFingerprinter (device-reduced when
    the link profile makes that profitable) and compares the aggregates.
    Returns True when the table matched — the caller skips the row-level
    pass; False on mismatch/error so row-level diagnosis runs.
    """
    from transferia_tpu.abstract.interfaces import is_columnar
    from transferia_tpu.columnar.batch import ColumnBatch
    from transferia_tpu.ops.rowhash import TableFingerprinter

    def run(storage: Storage):
        fp = TableFingerprinter(backend=params.fingerprint_backend)

        def pusher(batch):
            if is_columnar(batch):
                fp.push(batch)
                return
            rows = [it for it in _iter_rows(batch)]
            if rows:
                fp.push(ColumnBatch.from_rows(rows))

        storage.load_table(td, pusher)
        return fp.result()

    try:
        left = run(src)
        right = run(dst)
    except Exception as e:
        # an infrastructure error, not a data mismatch: record it in the
        # error map only and let the row-level pass decide table equality
        errors.add(tc.fqtn(), GENERIC_ERROR, f"fingerprint failed: {e}")
        return False
    tc.source_fingerprint = left.digest()
    tc.target_fingerprint = right.digest()
    if left == right:
        tc.strategy = "fingerprint"
        return True
    tc.mismatches.append(
        f"fingerprints differ: src={left.digest()} dst={right.digest()}")
    return False


def _positional_compare(tc: TableChecksum, errors: ErrorMap,
                        src: Storage, dst: Storage, td: TableDescription,
                        lcols: dict, rcols: dict,
                        params: ChecksumParameters) -> None:
    """Keyless tables: compare by position (best-effort)."""
    lrows: list[dict] = []
    rrows: list[dict] = []

    def lp(batch):
        lrows.extend(it.as_dict() for it in _iter_rows(batch))

    def rp(batch):
        rrows.extend(it.as_dict() for it in _iter_rows(batch))

    src.load_table(td, lp)
    dst.load_table(td, rp)
    if params.max_rows:
        lrows = lrows[:params.max_rows]
        rrows = rrows[:params.max_rows]
    for i, (a, b) in enumerate(zip(lrows, rrows)):
        _compare_rows(tc, a, b, (i,), lcols, rcols,
                      params.priority_comparators)


def checksum(source_storage: Storage, target_storage: Storage,
             tables: Optional[list[TableID]] = None,
             sample_rows: int = 1000,
             metrics: Optional[Metrics] = None,
             params: Optional[ChecksumParameters] = None) -> ChecksumReport:
    """Back-compat entry point (Checksum at checksum.go:140).

    Uses family-level type equality so the quick `check` command works on
    heterogeneous pairs out of the box, and honors sample_rows as the
    per-table compare cap (the old behavior)."""
    if params is None:
        params = ChecksumParameters(max_rows=sample_rows)
    return compare_checksum(source_storage, target_storage, tables,
                            params, equal_data_types=heterogeneous_data_types,
                            metrics=metrics)
