"""ActivateDelivery (pkg/worker/tasks/activate_delivery.go:27-180).

Flow: list tables -> primary-key checks -> destination cleanup per policy ->
provider Activate hook (or default cleanup+upload) -> mark activated (the
replicate command then starts the replication loop, start_job.go:15).
"""

from __future__ import annotations

import logging
from typing import Optional

from transferia_tpu.abstract.errors import AbortTransferError
from transferia_tpu.coordinator.interface import Coordinator, TransferStatus
from transferia_tpu.factories import new_storage
from transferia_tpu.models import CleanupPolicy, TransferType
from transferia_tpu.models.endpoint import capability
from transferia_tpu.providers.registry import ActivateCallbacks, get_provider
from transferia_tpu.stats.registry import Metrics
from transferia_tpu.tasks.snapshot import SnapshotLoader

logger = logging.getLogger(__name__)


def activate_delivery(transfer, coordinator: Coordinator,
                      metrics: Optional[Metrics] = None,
                      operation_id: Optional[str] = None) -> None:
    """Activation with rollback discipline (activate_delivery.go:27 uses
    util.Rollbacks the same way): source-side resources acquired during a
    failed activation — e.g. replication slots — are released."""
    from transferia_tpu.utils.rollbacks import Rollbacks

    metrics = metrics or Metrics()
    coordinator.set_status(transfer.id, TransferStatus.ACTIVATING)
    rollbacks = Rollbacks()
    try:
        loader = SnapshotLoader(transfer, coordinator,
                                operation_id=operation_id, metrics=metrics)
        tables = None
        if transfer.type.has_snapshot:
            storage = new_storage(transfer, metrics)
            try:
                tables = loader.filtered_table_list(storage)
                if not tables:
                    raise AbortTransferError(
                        "no tables match the transfer's include list"
                    )
                _check_primary_keys(transfer, storage, tables)
            finally:
                storage.close()

        dst_provider = get_provider(transfer.dst_provider(), transfer,
                                    metrics)

        def cleanup_cb(tbls):
            if transfer.dst.cleanup_policy != CleanupPolicy.DISABLED:
                logger.info("cleanup (%s): %d tables",
                            transfer.dst.cleanup_policy.value,
                            len(tbls or []))
                dst_provider.cleanup(tbls or [])

        def upload_cb(tbls):
            # a2 sources snapshot through the event pipeline
            # (load_snapshot_v2.go path for IsAbstract2 transfers)
            sp = src_provider.snapshot_provider()
            if sp is not None:
                from transferia_tpu.tasks.snapshot_v2 import upload_v2

                upload_v2(transfer, coordinator, sp, metrics)
                return
            loader.upload_tables(tbls)

        src_provider = get_provider(transfer.src_provider(), transfer,
                                    metrics)
        # Provider activate hooks that acquire source resources register
        # undos on callbacks.rollbacks (never registered eagerly here:
        # tearing down a pre-existing slot on a destination-side failure
        # would lose the WAL position of a previous activation).
        if transfer.type == TransferType.SNAPSHOT_AND_INCREMENT:
            # The replication slot/changefeed must exist BEFORE the
            # first snapshot row is read: changes committed while the
            # snapshot runs are only replayable if the slot already
            # pins the pre-snapshot LSN — created after the snapshot,
            # the slot starts at a post-snapshot position and the
            # in-between window is silently lost.  The provider hook
            # runs slot creation only (no-op callbacks); cleanup and
            # upload follow explicitly.
            if src_provider.supports_activate():
                src_provider.activate(
                    ActivateCallbacks(lambda _t: None, lambda _t: None,
                                      rollbacks)
                )
            cleanup_cb(tables)
            if coordinator.supports_mvcc() and \
                    src_provider.snapshot_provider() is None:
                # consistent cutover through the MVCC staging store:
                # snapshot parts land as base versions, deltas captured
                # during the load stack as layers, and the sealed
                # watermark is where replication resumes
                from transferia_tpu.mvcc.runner import (
                    activate_snapshot_and_increment,
                )

                activate_snapshot_and_increment(
                    transfer, coordinator, metrics, tables)
            else:
                upload_cb(tables)
        elif transfer.type.has_snapshot:
            if src_provider.supports_activate():
                src_provider.activate(
                    ActivateCallbacks(cleanup_cb, upload_cb, rollbacks)
                )
            else:
                cleanup_cb(tables)
                upload_cb(tables)
        elif transfer.type == TransferType.INCREMENT_ONLY:
            # replication-only: provider hook for slot/changefeed creation
            if src_provider.supports_activate():
                src_provider.activate(
                    ActivateCallbacks(cleanup_cb, lambda _t: None,
                                      rollbacks)
                )
        # pg_dump-style DDL objects (indexes/views/sequences) move to the
        # target after rows land (pkg/providers/postgres/pg_dump.go)
        if transfer.type != TransferType.INCREMENT_ONLY and \
                hasattr(src_provider, "transfer_ddl_objects"):
            src_provider.transfer_ddl_objects(transfer.dst)
        # dbt steps run against the target once the snapshot landed
        # (reference: registry/dbt pluggable_transformer at sink Close,
        # main worker only) — never for replication-only transfers where
        # no snapshot exists to transform
        if transfer.type != TransferType.INCREMENT_ONLY:
            from transferia_tpu.transform.plugins.dbt import (
                run_dbt_transformations,
            )

            run_dbt_transformations(transfer, coordinator)
        rollbacks.cancel()
        coordinator.set_status(transfer.id, TransferStatus.ACTIVATED)
        coordinator.set_transfer_state(transfer.id, {"status": "activated"})
    except BaseException as e:
        coordinator.set_status(transfer.id, TransferStatus.FAILED)
        coordinator.open_status_message(transfer.id, "activate", str(e))
        try:
            rollbacks.run()
        except Exception:
            logger.exception("activation rollback errors")
        raise


def _check_primary_keys(transfer, storage, tables) -> None:
    """PK checks (activate_delivery.go:118-131): warn on key-less tables;
    abort when the destination requires keys (e.g. CDC into keyed stores)."""
    requires_pk = capability(transfer.dst, "requires_primary_key", False) \
        or transfer.type.has_replication
    for td in tables:
        schema = storage.table_schema(td.id)
        if schema is not None and not schema.has_primary_key():
            msg = f"table {td.id} has no primary key"
            if requires_pk and transfer.type.has_replication:
                raise AbortTransferError(
                    msg + " — replication requires primary keys"
                )
            logger.warning("%s — updates/deletes cannot be matched", msg)
