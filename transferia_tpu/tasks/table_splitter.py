"""Table splitter (pkg/worker/tasks/table_splitter/table_splitter.go:14-75).

Splits tables into parallel parts when the source storage implements
ShardingStorage and the destination accepts sharded writes; sorts parts
big-first so stragglers start early.
"""

from __future__ import annotations

import logging
from typing import Optional

from transferia_tpu.abstract.interfaces import ShardingStorage, Storage
from transferia_tpu.abstract.table import OperationTablePart, TableDescription
from transferia_tpu.models.endpoint import capability

logger = logging.getLogger(__name__)


def split_tables(storage: Storage, tables: list[TableDescription],
                 transfer, operation_id: str) -> list[OperationTablePart]:
    """Build the operation part queue for a snapshot."""
    shardeable_dst = capability(transfer.dst, "is_shardeable", True)
    parts: list[OperationTablePart] = []
    for td in tables:
        descriptions = [td]
        if shardeable_dst and isinstance(storage, ShardingStorage):
            try:
                descriptions = storage.shard_table(td) or [td]
            except Exception as e:  # non-fatal: fall back to whole table
                logger.warning("shard_table(%s) failed, loading whole: %s",
                               td.id, e)
                descriptions = [td]
        n = len(descriptions)
        for i, d in enumerate(descriptions):
            parts.append(OperationTablePart(
                operation_id=operation_id,
                table_id=d.id,
                filter=d.filter,
                offset=d.offset,
                part_index=i,
                parts_count=n,
                eta_rows=d.eta_rows,
            ))
    # big-first ordering (table_splitter.go sorts by size desc)
    parts.sort(key=lambda p: -p.eta_rows)
    return parts
