"""Worker tasks / operations (reference: pkg/worker/tasks/)."""

from transferia_tpu.tasks.activate import activate_delivery
from transferia_tpu.tasks.checksum import ChecksumReport, checksum
from transferia_tpu.tasks.operations import (
    add_tables,
    apply_persisted_include_list,
    remove_tables,
    reupload,
)
from transferia_tpu.tasks.snapshot import SnapshotLoader
from transferia_tpu.tasks.table_splitter import split_tables
from transferia_tpu.tasks.upload import upload

__all__ = [
    "activate_delivery",
    "add_tables",
    "apply_persisted_include_list",
    "checksum",
    "ChecksumReport",
    "remove_tables",
    "reupload",
    "SnapshotLoader",
    "split_tables",
    "upload",
]
